"""paddle.distribution — probability distributions.

Reference: python/paddle/distribution/ (distribution.py Distribution
base; normal.py, uniform.py, categorical.py, bernoulli.py,
exponential.py; kl.py kl_divergence registry).

TPU-native: sampling draws threefry keys from the global generator
(core/generator.py), and every density/KL computation is built from
registry Tensor ops — NOT raw jnp — so gradients flow to distribution
parameters through the standard autograd tape (reparameterized VAE-style
losses train; verified by the drive: KL(Normal(mu,1) || N(0,1)) descends
on mu).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import generator as gen
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import API as _ops

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Gumbel", "Laplace", "kl_divergence", "register_kl"]

_LOG2PI = math.log(2.0 * math.pi)


def _t(x) -> Tensor:
    """To Tensor WITHOUT detaching (grads flow to learnable params)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, jnp.float32) if not hasattr(x, "dtype")
                  else jnp.asarray(x))


def _draw(shape, sampler) -> Tensor:
    """A stop-gradient random draw with the global generator's key."""
    return Tensor._from_data(sampler(gen.active_key(), tuple(shape)))


class Distribution:
    """Base API (reference distribution/distribution.py:46)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(d) for d in batch_shape)
        self._event_shape = tuple(int(d) for d in event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _ops["exp"](self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return tuple(int(s) for s in shape) + self._batch_shape


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    @property
    def mean(self):
        return _ops["broadcast_to"](self.loc, list(self._batch_shape)) \
            if self._batch_shape else self.loc

    @property
    def variance(self):
        v = _ops["square"](self.scale)
        return _ops["broadcast_to"](v, list(self._batch_shape)) \
            if self._batch_shape else v

    @property
    def stddev(self):
        return self.scale

    def rsample(self, shape=()):
        eps = _draw(self._extend(shape), jax.random.normal)
        return self.loc + self.scale * eps

    sample = rsample

    def log_prob(self, value):
        v = _t(value)
        var = _ops["square"](self.scale)
        return -_ops["square"](v - self.loc) / (2.0 * var) \
            - _ops["log"](self.scale) - 0.5 * _LOG2PI

    def entropy(self):
        out = _ops["log"](self.scale) + (0.5 + 0.5 * _LOG2PI)
        return _ops["broadcast_to"](out, list(self._batch_shape)) \
            if self._batch_shape else out


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(tuple(self.low.shape),
                                              tuple(self.high.shape)))

    def rsample(self, shape=()):
        u = _draw(self._extend(shape), jax.random.uniform)
        return self.low + (self.high - self.low) * u

    sample = rsample

    def log_prob(self, value):
        v = _t(value)
        inside = _ops["logical_and"](_ops["greater_equal"](v, self.low),
                                     _ops["less_than"](v, self.high))
        lp = -_ops["log"](self.high - self.low)
        neg_inf = Tensor(jnp.float32(-jnp.inf))
        return _ops["where"](inside, lp + v * 0.0, neg_inf + v * 0.0)

    def entropy(self):
        out = _ops["log"](self.high - self.low)
        return _ops["broadcast_to"](out, list(self._batch_shape)) \
            if self._batch_shape else out


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = _ops["log"](_ops["clip"](_t(probs), 1e-38, None))
        super().__init__(tuple(self.logits.shape[:-1]))

    @property
    def probs(self):
        return _ops["softmax"](self.logits, axis=-1)

    def sample(self, shape=()):
        out = jax.random.categorical(
            gen.active_key(), self.logits._data,
            shape=tuple(shape) + self._batch_shape)
        return Tensor._from_data(out.astype(jnp.int64))

    def log_prob(self, value):
        v = _t(value)
        logp = _ops["log_softmax"](self.logits, axis=-1)
        idx = _ops["unsqueeze"](_ops["cast"](v, "int32"), -1)
        picked = _ops["take_along_axis"](logp, idx, axis=-1)
        return _ops["squeeze"](picked, -1)

    def entropy(self):
        logp = _ops["log_softmax"](self.logits, axis=-1)
        return -_ops["sum"](_ops["exp"](logp) * logp, axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs_ = _t(probs)
            self.logits_ = _ops["log"](self.probs_) \
                - _ops["log1p"](-self.probs_)
        elif logits is not None:
            self.logits_ = _t(logits)
            self.probs_ = _ops["sigmoid"](self.logits_)
        else:
            raise ValueError("need probs or logits")
        super().__init__(tuple(self.probs_.shape))

    @property
    def mean(self):
        return self.probs_

    @property
    def variance(self):
        return self.probs_ * (1.0 - self.probs_)

    def sample(self, shape=()):
        u = _draw(self._extend(shape), jax.random.uniform)
        return _ops["cast"](_ops["less_than"](u, self.probs_ + u * 0.0),
                            "float32")

    def _log_sigmoid(self, x):
        # log sigmoid(x) = -softplus(-x), numerically stable
        return -_ops["log1p"](_ops["exp"](-_ops["abs"](x))) \
            + _ops["minimum"](x, x * 0.0)

    def log_prob(self, value):
        v = _t(value)
        return v * self._log_sigmoid(self.logits_) \
            + (1.0 - v) * self._log_sigmoid(-self.logits_)

    def entropy(self):
        p = self.probs_
        pc = _ops["clip"](p, 1e-38, None)
        qc = _ops["clip"](1.0 - p, 1e-38, None)
        return -(p * _ops["log"](pc) + (1.0 - p) * _ops["log"](qc))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / _ops["square"](self.rate)

    def rsample(self, shape=()):
        e = _draw(self._extend(shape), jax.random.exponential)
        return e / self.rate

    sample = rsample

    def log_prob(self, value):
        return _ops["log"](self.rate) - self.rate * _t(value)

    def entropy(self):
        return 1.0 - _ops["log"](self.rate)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    def rsample(self, shape=()):
        g = _draw(self._extend(shape), jax.random.gumbel)
        return self.loc + self.scale * g

    sample = rsample

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return -(z + _ops["exp"](-z)) - _ops["log"](self.scale)

    def entropy(self):
        out = _ops["log"](self.scale) + 1.5772156649  # 1 + Euler gamma
        return _ops["broadcast_to"](out, list(self._batch_shape)) \
            if self._batch_shape else out


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    def rsample(self, shape=()):
        l = _draw(self._extend(shape), jax.random.laplace)
        return self.loc + self.scale * l

    sample = rsample

    def log_prob(self, value):
        return -_ops["abs"](_t(value) - self.loc) / self.scale \
            - _ops["log"](2.0 * self.scale)

    def entropy(self):
        out = _ops["log"](2.0 * self.scale) + 1.0
        return _ops["broadcast_to"](out, list(self._batch_shape)) \
            if self._batch_shape else out


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a KL(p || q) rule for a distribution pair
    (reference distribution/kl.py register_kl); user rules take
    precedence over the built-ins."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    """KL(p || q) for registered pairs (reference distribution/kl.py);
    differentiable w.r.t. both distributions' parameters. Dispatch
    picks the MOST SPECIFIC matching pair (reference total_ordering) —
    builtins are ordinary registry entries, so a user rule for the same
    pair overrides them, but a base-class fallback never shadows a more
    specific rule."""
    matches = [(cp, cq, fn) for (cp, cq), fn in _KL_REGISTRY.items()
               if isinstance(p, cp) and isinstance(q, cq)]
    if not matches:
        raise NotImplementedError(
            f"kl_divergence not registered for "
            f"({type(p).__name__}, {type(q).__name__})")

    def specificity(m):
        cp, cq, _ = m
        # deeper in each MRO = more specific; registration order breaks
        # exact ties LIFO (later registrations win), matching reference
        return (len(type(p).__mro__) - type(p).__mro__.index(cp)
                + len(type(q).__mro__) - type(q).__mro__.index(cq),
                list(_KL_REGISTRY).index((cp, cq)))

    return max(matches, key=specificity)[2](p, q)


def _kl_normal_normal(p, q):
    var_ratio = _ops["square"](p.scale / q.scale)
    t1 = _ops["square"]((p.loc - q.loc) / q.scale)
    return 0.5 * (var_ratio + t1 - 1.0 - _ops["log"](var_ratio))


def _kl_categorical(p, q):
    lp = _ops["log_softmax"](p.logits, axis=-1)
    lq = _ops["log_softmax"](q.logits, axis=-1)
    return _ops["sum"](_ops["exp"](lp) * (lp - lq), axis=-1)


def _kl_uniform(p, q):
    return _ops["log"]((q.high - q.low) / (p.high - p.low))


def _kl_bernoulli(p, q):
    eps = 1e-7
    a = _ops["clip"](p.probs_, eps, 1 - eps)
    b = _ops["clip"](q.probs_, eps, 1 - eps)
    return a * _ops["log"](a / b) \
        + (1.0 - a) * _ops["log"]((1.0 - a) / (1.0 - b))


def _kl_exponential(p, q):
    r = p.rate / q.rate
    return _ops["log"](r) + 1.0 / r - 1.0


_KL_REGISTRY[(Normal, Normal)] = _kl_normal_normal
_KL_REGISTRY[(Categorical, Categorical)] = _kl_categorical
_KL_REGISTRY[(Uniform, Uniform)] = _kl_uniform
_KL_REGISTRY[(Bernoulli, Bernoulli)] = _kl_bernoulli
_KL_REGISTRY[(Exponential, Exponential)] = _kl_exponential


# ---------------------------------------------------------------------------
# wider zoo + transforms (reference: beta.py, gamma.py, dirichlet.py,
# lognormal.py, cauchy.py, studentT, multivariate_normal.py, poisson.py,
# geometric.py, binomial.py, multinomial.py, continuous_bernoulli.py,
# independent.py, transform.py, transformed_distribution.py)
# ---------------------------------------------------------------------------
from paddle_tpu.distribution.extra import (  # noqa: F401,E402
    AbsTransform, AffineTransform, Beta, Binomial, Cauchy, ChainTransform,
    ContinuousBernoulli, Dirichlet, ExponentialFamily, ExpTransform, Gamma,
    Geometric, Independent, IndependentTransform, LogNormal, Multinomial,
    MultivariateNormal, Poisson, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, StudentT, TanhTransform, Transform,
    TransformedDistribution,
)

__all__ += [
    "Beta", "Binomial", "Cauchy", "ContinuousBernoulli", "Dirichlet",
    "ExponentialFamily", "Gamma", "Geometric", "Independent", "LogNormal",
    "Multinomial", "MultivariateNormal", "Poisson", "StudentT",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]


def _kl_extra(p, q):
    """Additional registered KL pairs (reference distribution/kl.py)."""
    if isinstance(p, Beta) and isinstance(q, Beta):
        sp = p.alpha + p.beta
        dg = _ops["digamma"]
        return ((_ops["lgamma"](q.alpha) + _ops["lgamma"](q.beta)
                 - _ops["lgamma"](q.alpha + q.beta))
                - (_ops["lgamma"](p.alpha) + _ops["lgamma"](p.beta)
                   - _ops["lgamma"](sp))
                + (p.alpha - q.alpha) * dg(p.alpha)
                + (p.beta - q.beta) * dg(p.beta)
                + (q.alpha + q.beta - p.alpha - p.beta) * dg(sp))
    if isinstance(p, Gamma) and isinstance(q, Gamma):
        dg = _ops["digamma"]
        return ((p.concentration - q.concentration) * dg(p.concentration)
                - _ops["lgamma"](p.concentration)
                + _ops["lgamma"](q.concentration)
                + q.concentration * (_ops["log"](p.rate)
                                     - _ops["log"](q.rate))
                + p.concentration * (q.rate / p.rate - 1.0))
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        dg = _ops["digamma"]
        a0 = _ops["sum"](p.concentration, axis=-1, keepdim=True)
        t = (p.concentration - q.concentration) * (
            dg(p.concentration) - dg(a0))
        return (_ops["lgamma"](_ops["sum"](p.concentration, axis=-1))
                - _ops["lgamma"](_ops["sum"](q.concentration, axis=-1))
                - _ops["sum"](_ops["lgamma"](p.concentration), axis=-1)
                + _ops["sum"](_ops["lgamma"](q.concentration), axis=-1)
                + _ops["sum"](t, axis=-1))
    if isinstance(p, Poisson) and isinstance(q, Poisson):
        return p.rate * (_ops["log"](p.rate) - _ops["log"](q.rate)) \
            - p.rate + q.rate
    if isinstance(p, Geometric) and isinstance(q, Geometric):
        a, b = p.probs, q.probs
        return (_ops["log"](a) - _ops["log"](b)) + (1.0 - a) / a * (
            _ops["log"](1.0 - a) - _ops["log"](1.0 - b))
    if isinstance(p, LogNormal) and isinstance(q, LogNormal):
        return kl_divergence(p._normal, q._normal)
    return None


_kl_base = kl_divergence


def kl_divergence(p, q):  # noqa: F811
    extra = _kl_extra(p, q)
    if extra is not None:
        return extra
    return _kl_base(p, q)
