"""paddle.version (reference: generated python/paddle/version/__init__.py).

The TPU build tracks the reference's API surface as of the 2.6→3.0-dev
transition snapshot; `full_version` reflects that compatibility level.
"""
major = "3"
minor = "0"
patch = "0"
rc = 0
full_version = f"{major}.{minor}.{patch}"
commit = "tpu-native"
istaged = True

cuda_version = "False"   # reference strings: version or 'False'
cudnn_version = "False"
xpu_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"commit: {commit}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
