"""Test-support utilities shipped with the package so downstream test
suites can reuse them (reference: paddle's test/legacy_test helpers are
importable from installed wheels).

``paddle_tpu.testing.faults`` is the fault-injection harness backing the
fault-tolerance tests (crash/raise/sleep at named points inside the
checkpoint writer, torn-file helpers, child-process killers).
"""
from paddle_tpu.testing import faults  # noqa: F401

__all__ = ["faults"]
