"""Fault-injection harness for robustness tests.

Production code calls :func:`fire` at named fault points (the checkpoint
writer's commit protocol, etc.). With no faults installed the call is a
dict lookup on an empty dict — effectively free — so the hooks stay in
production code permanently, the same way the reference keeps
FLAGS-gated fault hooks compiled into comm_task_manager.

Faults are installed either programmatically (:func:`install`, or the
:func:`injected` context manager) or through the ``PADDLE_FAULTS``
environment variable, which is how subprocess end-to-end tests tell a
worker where to die. Spec grammar (specs separated by ``;``)::

    point:action[:arg][@skip][*times]

    ckpt.data_written:raise            raise OSError at every hit
    ckpt.before_marker:crash@2         os._exit on the 3rd hit
    ckpt.data_written:sleep:60*1       sleep 60s, first hit only
    ckpt.data_written:touch:/tmp/f     create /tmp/f and continue

``@skip`` ignores the first N hits; ``*times`` fires at most N times
(for per-step points like ``serving.step`` or the router's
``fleet.kill_replica`` / ``fleet.drain_replica`` / ``fleet.slow_replica``
/ ``fleet.worker_kill`` — queried once per step — ``@skip`` counts
steps; the fleet transport's ``fleet.rpc_delay`` / ``fleet.rpc_drop``
fire once per RPC attempt, so ``@skip`` counts calls; the peer data
plane's ``fleet.peer_connect_fail`` / ``fleet.peer_send_drop`` /
``fleet.peer_frame_corrupt`` / ``fleet.peer_stall`` fire once per
``peer_push`` attempt; ``serving.kv_scatter`` fires inside the engine's
KV/prefix import between block allocation and scatter — ``raise`` there
exercises the partial-failure cleanup path. The replicated control
plane adds three KEYED flag points — the consumer passes ``key=`` to
:func:`check` so a targeted fault is only consumed by the consumer it
names: ``fleet.router_kill:flag:<router_id>`` is queried once per
router step and makes that router go silent in place, the in-process
equivalent of SIGKILLing it; ``fleet.lease_expire:flag:<rid>`` is
queried at every lease renewal and drops that request's renewal write
while returning failure, forcing the owner to self-fence; and
``fleet.lease_steal:flag[:<rid>]`` is queried by the adoption sweep and
force-adopts a live foreign lease, exercising the expiry-race path
without waiting out a TTL).
Actions: ``crash`` (``os._exit(FAULT_EXIT)`` — no cleanup, no atexit,
the in-process equivalent of SIGKILL), ``raise`` (``OSError``),
``sleep:<seconds>``, ``touch:<path>`` (progress marker so a parent test
process knows the point was reached), ``sigterm`` (deliver SIGTERM to
the current process), ``flag`` (no side effect of its own — the
production code QUERIES it via :func:`check` and corrupts its own data
deterministically: the serving engine's NaN-logits and forced-OOM
points, where the fault must alter behavior rather than kill the
process).
"""
from __future__ import annotations

import os
import re
import signal
import time
from typing import Dict, List, Optional

__all__ = [
    "FAULT_EXIT", "FAULT_POINTS", "Fault", "FaultInjector", "fire",
    "check", "install", "clear", "injected", "active_injector",
    "tear_file", "child_pids", "kill_one_child", "wait_for_path",
    # registry constants (every production fault point, by name)
    "SERVING_FORCE_OOM", "SERVING_KV_SCATTER", "SERVING_STEP",
    "SERVING_NAN_LOGITS", "FLEET_PEER_CONNECT_FAIL", "FLEET_PEER_STALL",
    "FLEET_PEER_SEND_DROP", "FLEET_PEER_FRAME_CORRUPT",
    "FLEET_RPC_DELAY", "FLEET_RPC_DROP", "FLEET_KILL_REPLICA",
    "FLEET_DRAIN_REPLICA", "FLEET_SLOW_REPLICA", "FLEET_WORKER_KILL",
    "FLEET_ROUTER_KILL", "FLEET_LEASE_STEAL", "FLEET_LEASE_EXPIRE",
    "FLEET_PREFIX_SHIP_DROP", "FLEET_PREFIX_SHIP_CORRUPT",
    "FLEET_KV_SHIP_DELAY", "FLEET_KV_SHIP_DROP", "FLEET_KV_SHIP_CORRUPT",
    "CKPT_BEFORE_COMMIT", "CKPT_BEFORE_MARKER", "CKPT_COMMITTED",
    "CKPT_DATA_WRITTEN",
]

# -- the fault-point registry ----------------------------------------------
# Every production fault point, as a named constant: call sites reference
# these (the ``fault-point-literal`` lint rule enforces it), so a typo'd
# point can never silently stop firing, and the registry is the one list
# a coverage check can walk. Keyed points compose as f-strings LED by the
# constant: ``f"{faults.SERVING_FORCE_OOM}.{request_id}"``.

# serving engine (in-process data faults)
SERVING_FORCE_OOM = "serving.force_oom"        # keyed: .<request_id>
SERVING_KV_SCATTER = "serving.kv_scatter"
SERVING_STEP = "serving.step"
SERVING_NAN_LOGITS = "serving.nan_logits"

# fleet transport + peer data plane (per-RPC / per-push)
FLEET_PEER_CONNECT_FAIL = "fleet.peer_connect_fail"
FLEET_PEER_STALL = "fleet.peer_stall"
FLEET_PEER_SEND_DROP = "fleet.peer_send_drop"
FLEET_PEER_FRAME_CORRUPT = "fleet.peer_frame_corrupt"
FLEET_RPC_DELAY = "fleet.rpc_delay"
FLEET_RPC_DROP = "fleet.rpc_drop"

# fleet router (per-step chaos + replicated control plane; the last
# three are KEYED — see ``check(key=...)``)
FLEET_KILL_REPLICA = "fleet.kill_replica"
FLEET_DRAIN_REPLICA = "fleet.drain_replica"
FLEET_SLOW_REPLICA = "fleet.slow_replica"
FLEET_WORKER_KILL = "fleet.worker_kill"
FLEET_ROUTER_KILL = "fleet.router_kill"
FLEET_LEASE_STEAL = "fleet.lease_steal"
FLEET_LEASE_EXPIRE = "fleet.lease_expire"

# KV / prefix ship path
FLEET_PREFIX_SHIP_DROP = "fleet.prefix_ship_drop"
FLEET_PREFIX_SHIP_CORRUPT = "fleet.prefix_ship_corrupt"
FLEET_KV_SHIP_DELAY = "fleet.kv_ship_delay"
FLEET_KV_SHIP_DROP = "fleet.kv_ship_drop"
FLEET_KV_SHIP_CORRUPT = "fleet.kv_ship_corrupt"

# checkpoint commit protocol
CKPT_BEFORE_COMMIT = "ckpt.before_commit"
CKPT_BEFORE_MARKER = "ckpt.before_marker"
CKPT_COMMITTED = "ckpt.committed"
CKPT_DATA_WRITTEN = "ckpt.data_written"

FAULT_POINTS = frozenset({
    SERVING_FORCE_OOM, SERVING_KV_SCATTER, SERVING_STEP,
    SERVING_NAN_LOGITS, FLEET_PEER_CONNECT_FAIL, FLEET_PEER_STALL,
    FLEET_PEER_SEND_DROP, FLEET_PEER_FRAME_CORRUPT, FLEET_RPC_DELAY,
    FLEET_RPC_DROP, FLEET_KILL_REPLICA, FLEET_DRAIN_REPLICA,
    FLEET_SLOW_REPLICA, FLEET_WORKER_KILL, FLEET_ROUTER_KILL,
    FLEET_LEASE_STEAL, FLEET_LEASE_EXPIRE, FLEET_PREFIX_SHIP_DROP,
    FLEET_PREFIX_SHIP_CORRUPT, FLEET_KV_SHIP_DELAY, FLEET_KV_SHIP_DROP,
    FLEET_KV_SHIP_CORRUPT, CKPT_BEFORE_COMMIT, CKPT_BEFORE_MARKER,
    CKPT_COMMITTED, CKPT_DATA_WRITTEN,
})

# exit code for the "crash" action: distinct from every code the runtime
# uses (watchdog 6, gang-abort 7, launch re-form 75) so tests can assert
# the process died AT the injected point and not from collateral damage
FAULT_EXIT = 41

ENV_VAR = "PADDLE_FAULTS"

_SPEC_RE = re.compile(
    r"^(?P<point>[^:@*]+):(?P<action>[^:@*]+)"
    r"(?::(?P<arg>[^@*]*))?(?:@(?P<skip>\d+))?(?:\*(?P<times>\d+))?$")


class Fault:
    """One installed fault: where to fire, what to do, and how often."""

    def __init__(self, point: str, action: str, arg: Optional[str] = None,
                 skip: int = 0, times: Optional[int] = None):
        self.point = point
        self.action = action
        self.arg = arg
        self.skip = int(skip)
        self.times = times  # None = unlimited
        self.hits = 0       # calls that reached the point
        self.fired = 0      # calls that actually performed the action

    @staticmethod
    def parse(spec: str) -> "Fault":
        m = _SPEC_RE.match(spec.strip())
        if m is None:
            raise ValueError(f"bad fault spec {spec!r} "
                             f"(want point:action[:arg][@skip][*times])")
        return Fault(m["point"], m["action"], m["arg"],
                     int(m["skip"] or 0),
                     None if m["times"] is None else int(m["times"]))

    def _perform(self):
        if self.action == "crash":
            # hard death: no cleanup, buffered IO lost — what SIGKILL or
            # a power cut does to a half-written checkpoint
            os._exit(FAULT_EXIT)
        if self.action == "raise":
            raise OSError(f"injected fault at {self.point!r}")
        if self.action == "sleep":
            time.sleep(float(self.arg or 1.0))
            return
        if self.action == "touch":
            with open(self.arg, "w") as f:
                f.write(f"{self.point}\n")
            return
        if self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if self.action == "flag":
            return  # queried via check(); no side effect of its own
        raise ValueError(f"unknown fault action {self.action!r}")

    def fire(self) -> bool:
        """Returns True iff the action was actually performed this hit
        (past ``@skip``, within ``*times``)."""
        self.hits += 1
        if self.hits <= self.skip:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        self._perform()
        return True


class FaultInjector:
    def __init__(self, spec: str = ""):
        self._by_point: Dict[str, List[Fault]] = {}
        for part in (spec or "").split(";"):
            if part.strip():
                self.add(Fault.parse(part))

    def add(self, fault: Fault) -> Fault:
        self._by_point.setdefault(fault.point, []).append(fault)
        return fault

    def faults(self, point: Optional[str] = None) -> List[Fault]:
        if point is not None:
            return list(self._by_point.get(point, []))
        return [f for fs in self._by_point.values() for f in fs]

    def fire(self, point: str):
        for f in self._by_point.get(point, ()):
            f.fire()

    def check(self, point: str,
              key: Optional[str] = None) -> List[Optional[str]]:
        """Fire the point and return the ``arg`` of every ``flag`` fault
        that performed this hit (empty when none did). Non-flag faults
        installed at the same point fire their actions as usual.

        ``key`` scopes targeted flags in multi-consumer points: a flag
        fault whose ``arg`` names a specific target only HITS (and so
        only burns ``@skip``/``*times`` budget) when ``key`` matches it
        — an argless flag matches every key. Without this, N routers
        polling the same point would race to consume a ``*1`` fault
        aimed at just one of them."""
        out: List[Optional[str]] = []
        for f in self._by_point.get(point, ()):
            if (key is not None and f.action == "flag"
                    and f.arg not in (None, "", key)):
                continue  # targeted at someone else: not a hit
            if f.fire() and f.action == "flag":
                out.append(f.arg)
        return out


_active = FaultInjector(os.environ.get(ENV_VAR, ""))


def active_injector() -> FaultInjector:
    return _active


def fire(point: str):
    """Production-side hook: perform any fault installed at ``point``."""
    if _active._by_point:
        _active.fire(point)


def check(point: str, key: Optional[str] = None) -> List[Optional[str]]:
    """Production-side hook for data-corruption faults: fire ``point``
    and return the args of the ``flag`` faults that performed, so the
    caller can deterministically poison its own state (e.g. the serving
    engine's NaN-logits row, BlockManager's forced OOM). ``key`` scopes
    targeted flags to one consumer (see :meth:`FaultInjector.check`).
    Free when no faults are installed."""
    if not _active._by_point:
        return []
    return _active.check(point, key)


def install(spec: str) -> FaultInjector:
    """Replace the active injector with one parsed from ``spec``;
    returns it (so tests can read per-fault hit counters)."""
    global _active
    _active = FaultInjector(spec)
    return _active


def clear():
    global _active
    _active = FaultInjector("")


class injected:
    """Context manager: install ``spec`` for the block, restore after.

    >>> with faults.injected("ckpt.data_written:raise"):
    ...     save_state_dict(state, path)   # dies mid-write
    """

    def __init__(self, spec: str):
        self.spec = spec
        self.injector: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        global _active
        self._prev = _active
        self.injector = _active = FaultInjector(self.spec)
        return self.injector

    def __exit__(self, *exc):
        global _active
        _active = self._prev
        return False


# -- test-side helpers (no production callers) ----------------------------
def tear_file(path: str, frac: float = 0.5):
    """Truncate ``path`` to ``frac`` of its size — a torn write, the
    on-disk state a crash mid-``write()`` leaves behind."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, int(size * frac)))


def child_pids(pid: Optional[int] = None) -> List[int]:
    """Direct children of ``pid`` (default: this process), via /proc."""
    ppid = os.getpid() if pid is None else pid
    out = []
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            if int(fields[1]) == ppid:  # field 4 overall = ppid
                out.append(int(d))
        except (OSError, IndexError, ValueError):
            continue
    return sorted(out)


def kill_one_child(sig: int = signal.SIGKILL,
                   pid: Optional[int] = None) -> Optional[int]:
    """SIGKILL one (the newest) child process — the injector for
    'DataLoader worker killed by the OOM killer'. Returns the pid killed,
    or None if there were no children."""
    kids = child_pids(pid)
    if not kids:
        return None
    victim = kids[-1]
    os.kill(victim, sig)
    return victim


def wait_for_path(path: str, timeout: float = 30.0) -> bool:
    """Poll until ``path`` exists (a ``touch`` fault's progress marker)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.02)
    return False
