"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py
(MoELayer:263, MoEScatter:99/MoEGather:149 over global_scatter/gather
CUDA all-to-all ops) and moe/gate/{naive,gshard,switch}_gate.py.

TPU-native design: dispatch/combine are einsum contractions against a
[tokens, experts, capacity] one-hot dispatch tensor (the GShard
formulation). Expert FFNs are vmapped over a stacked [E, ...] parameter
axis. Under a mesh with an ``ep`` axis the stacked expert dim and the
dispatched [E, C, M] activations are sharded over ``ep``, so XLA's GSPMD
partitioner lowers the dispatch einsum to exactly the all-to-all the
reference implements by hand — inside the one compiled train step.

Gate math follows the public GShard / Switch-Transformer recipes:
top-1 (switch) or top-2 (gshard) routing, per-expert capacity
C = ceil(T/E * capacity_factor), overflow tokens dropped, load-balancing
aux loss  E * sum_e(mean_gates_e * mean_routed_e).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet.mp_layers import mark_placements
from paddle_tpu.distributed.mesh import Shard
from paddle_tpu.jit.trace import functionalize
from paddle_tpu.ops import registry as _registry
from paddle_tpu.ops.registry import register_emitter as _register

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate"]


# ---------------------------------------------------------------------------
# gating (data-level)
# ---------------------------------------------------------------------------
def _top1_dispatch(logits, capacity):
    """Switch routing: (combine [T,E,C], dispatch [T,E,C], aux scalar)."""
    t, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(gates, axis=-1)                       # [T]
    mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)       # [T,E]
    # aux: E * sum_e mean(gates_e) * mean(routed_e)   (Switch eq. 4)
    aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(mask, axis=0))
    pos = jnp.cumsum(mask, axis=0) * mask - 1.0            # [T,E]
    keep = (pos < capacity) & (mask > 0)
    gate_val = jnp.sum(gates * mask, axis=-1)              # [T]
    pos_idx = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)
    cap_oh = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
    kept = jnp.any(keep, axis=-1).astype(jnp.float32)
    combine = (gate_val * kept)[:, None, None] * mask[:, :, None] \
        * cap_oh[:, None, :]
    dispatch = combine > 0.0
    return combine, dispatch, aux


def _top2_dispatch(logits, capacity, rand=None):
    """GShard top-2 routing. ``rand`` (uniform [T]) enables the GShard
    random-routing rule: the 2nd expert is used with probability
    min(1, 2*g2) (reference distributed/models/moe/utils.py:109
    _random_routing — drop when 2*value2 < prob)."""
    t, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    i1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(i1, e, dtype=jnp.float32)
    gates2 = gates * (1.0 - mask1)
    i2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(i2, e, dtype=jnp.float32)
    if rand is not None:
        g2_raw = jnp.sum(gates * mask2, axis=-1)
        mask2 = mask2 * (2.0 * g2_raw >= rand)[:, None].astype(jnp.float32)

    aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(mask1, axis=0))

    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - 1.0
    count1 = jnp.sum(mask1, axis=0, keepdims=True)         # [1,E]
    pos2 = (jnp.cumsum(mask2, axis=0) + count1) * mask2 - 1.0

    keep1 = (pos1 < capacity) & (mask1 > 0)
    keep2 = (pos2 < capacity) & (mask2 > 0)

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def one(gv, mask, pos, keep):
        pos_idx = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)
        cap_oh = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
        kept = jnp.any(keep, axis=-1).astype(jnp.float32)
        return (gv * kept)[:, None, None] * mask[:, :, None] \
            * cap_oh[:, None, :]

    combine = one(g1, mask1, pos1, keep1) + one(g2, mask2, pos2, keep2)
    dispatch = combine > 0.0
    return combine, dispatch, aux


@_register(name="moe_forward")
def _moe_forward_emitter(x, gate_w, leaves, apply_fn=None, k=2,
                         capacity=0, ep_axis=None, key=None,
                         switch_eps=0.0, random_routing=False):
    """x [T,M]; gate_w [M,E]; leaves: list of stacked [E,...] expert
    params. Returns (out [T,M], aux_loss scalar).

    key (a traced PRNG key when training, None in eval) drives the
    reference gates\' stochastic parts: SwitchGate\'s additive uniform
    logit noise drawn from [1-eps, 1+eps] (switch_gate.py:52-56 adds it;
    softmax is shift-invariant, so the effective jitter is the +-eps
    spread) and GShardGate\'s random second-expert routing
    (gshard_gate.py:76-83).
    """
    t, m = x.shape
    e = gate_w.shape[1]
    logits = jnp.dot(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    if k == 1:
        if key is not None and switch_eps > 0.0:
            k_noise, key = jax.random.split(key)
            noise = jax.random.uniform(
                k_noise, logits.shape, minval=1.0 - switch_eps,
                maxval=1.0 + switch_eps)
            logits = logits + noise
        combine, dispatch, aux = _top1_dispatch(logits, capacity)
    else:
        rand = None
        if key is not None and random_routing:
            k_rand, key = jax.random.split(key)
            rand = jax.random.uniform(k_rand, (t,))
        combine, dispatch, aux = _top2_dispatch(logits, capacity, rand)
    # dispatch: [T,E,C] x [T,M] -> [E,C,M]  (the all-to-all under GSPMD)
    expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(x.dtype), x)
    if ep_axis is not None:
        from paddle_tpu.distributed.engine import current_mesh
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = current_mesh()
        if mesh is not None and ep_axis in mesh.dim_names:
            expert_in = jax.lax.with_sharding_constraint(
                expert_in, NamedSharding(mesh.jax_mesh(),
                                         PartitionSpec(ep_axis)))
    expert_out = jax.vmap(apply_fn)(tuple(leaves), expert_in)  # [E,C,M]
    out = jnp.einsum("tec,ecm->tm", combine.astype(expert_out.dtype),
                     expert_out)
    return out.astype(x.dtype), aux.astype(jnp.float32)


if "moe_forward" not in _registry.OPS:
    _registry.build_registry([
        {"op": "moe_forward", "tensor_args": ["x", "gate_w", "*leaves"],
         "methods": []}])


# ---------------------------------------------------------------------------
# gate layers (API parity with reference moe/gate/*.py)
# ---------------------------------------------------------------------------
class NaiveGate(nn.Layer):
    """Linear router; k=2 like the reference NaiveGate."""

    top_k = 2

    def __init__(self, d_model, num_experts):
        super().__init__()
        self.weight = self.create_parameter(
            [d_model, num_experts],
            default_initializer=nn.initializer.XavierUniform())


class GShardGate(NaiveGate):
    """Top-2 with random second-expert routing and train/eval capacity
    factors (reference gshard_gate.py:31 — capacity=(1.2, 2.4),
    random_routing=True)."""

    top_k = 2

    def __init__(self, d_model, num_experts, capacity=(1.2, 2.4),
                 random_routing=True):
        super().__init__(d_model, num_experts)
        self.capacity = tuple(capacity)
        self.random_routing = random_routing


class SwitchGate(NaiveGate):
    """Top-1 with additive uniform logit noise while training
    (reference switch_gate.py:31 — switch_eps=0.1,
    capacity=(1.2, 2.4))."""

    top_k = 1

    def __init__(self, d_model, num_experts, switch_eps=0.1,
                 capacity=(1.2, 2.4)):
        super().__init__(d_model, num_experts)
        self.switch_eps = switch_eps
        self.capacity = tuple(capacity)


_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


# ---------------------------------------------------------------------------
# MoELayer
# ---------------------------------------------------------------------------
class MoELayer(nn.Layer):
    """Reference MoELayer:263 contract: a list of per-rank experts + a
    gate; here experts are stacked on a leading [E, ...] axis marked for
    ``ep`` sharding, and the whole dispatch/compute/combine runs inside
    the compiled step.

    The load-balancing aux loss of the last forward is available as
    ``self.aux_loss`` (a Tensor) — add ``aux_loss_weight * layer.aux_loss``
    to the training loss.
    """

    def __init__(self, d_model: int, experts: Sequence[nn.Layer],
                 gate: str | nn.Layer = "gshard",
                 capacity_factor: Optional[float] = None,
                 ep_axis: Optional[str] = "ep"):
        super().__init__()
        self.d_model = d_model
        self.num_experts = len(experts)
        # None: defer to the gate's (train, eval) capacity factors;
        # an explicit value always wins over the gate defaults
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        if isinstance(gate, str):
            gate = _GATES[gate](d_model, self.num_experts)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", 2)

        # functionalize one expert as template; stack leaves across experts
        template = experts[0]
        self._expert_apply, (_, tmpl_params), (_, tmpl_buf) = \
            functionalize(template)
        if tmpl_buf:
            raise NotImplementedError(
                "MoE experts with buffers (BatchNorm) are unsupported; "
                "use LayerNorm/RMSNorm")
        per_expert: List[List[Tensor]] = []
        for ex in experts:
            _, (_, ps), _ = functionalize(ex)
            if len(ps) != len(tmpl_params):
                raise ValueError("experts must share one structure")
            per_expert.append(ps)
        self._n_leaves = len(tmpl_params)
        self.stacked_params = []
        for i in range(self._n_leaves):
            stacked = jnp.stack([per_expert[e][i]._data
                                 for e in range(self.num_experts)])
            p = nn.Parameter(stacked)
            if ep_axis:
                mark_placements(p, **{ep_axis: Shard(0)})
            self.add_parameter(f"expert_leaf_{i}", p)
            self.stacked_params.append(p)
        self.aux_loss = None

    def _apply_one_expert(self, leaves, xe):
        from paddle_tpu.core import generator as gen

        out, _ = self._expert_apply(list(leaves), [], gen.active_key(), xe)
        return out

    def forward(self, x):
        from paddle_tpu.core import generator as gen

        shape = x.shape
        t = int(np.prod(shape[:-1]))
        x2 = x.reshape([t, shape[-1]])
        # train/eval capacity factors from the gate when it defines them
        # (reference capacity=(1.2, 2.4)); fall back to the layer factor
        gate_caps = getattr(self.gate, "capacity", None)
        if self.capacity_factor is not None:
            factor = self.capacity_factor
        elif gate_caps is not None:
            factor = gate_caps[0 if self.training else 1]
        else:
            factor = 1.25
        capacity = int(np.ceil(t / self.num_experts * factor))
        key = gen.active_key() if self.training else None
        out, aux = _registry.API["moe_forward"](
            x2, self.gate.weight, list(self.stacked_params),
            apply_fn=self._apply_one_expert, k=self.top_k,
            capacity=max(capacity, 1), ep_axis=self.ep_axis, key=key,
            switch_eps=getattr(self.gate, "switch_eps", 0.0),
            random_routing=getattr(self.gate, "random_routing", False))
        self.aux_loss = aux
        return out.reshape(shape)
