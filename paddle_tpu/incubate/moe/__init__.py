from paddle_tpu.incubate.moe.moe_layer import (  # noqa: F401
    GShardGate, MoELayer, NaiveGate, SwitchGate,
)

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate"]
