"""MoE-aware global-norm gradient clipping.

Reference: python/paddle/incubate/distributed/models/moe/grad_clip.py
(ClipGradForMOEByGlobalNorm) — there expert parameters are *different*
objects on every ep rank, so the expert-norm contribution must be
all-reduced over the moe group before combining with the normal-param
norm. In this framework expert parameters are global-view stacked
[E, ...] tensors (sharded over ep by GSPMD), so their grads already
cover every expert; the cross-rank reduction is subsumed and the math
reduces to one global norm over both groups — computed here exactly in
the reference's two-bucket form so ``is_expert_param_func`` keeps its
filtering role (and tests can assert the split).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase

__all__ = ["ClipGradForMOEByGlobalNorm"]


def _sum_sq(grads):
    tot = jnp.zeros((), jnp.float32)
    for g in grads:
        tot = tot + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return tot


class ClipGradForMOEByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        self.clip_norm = float(clip_norm)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group
        self.group_name = group_name

    def _split(self, params_grads):
        normal, expert = [], []
        for p, g in params_grads:
            if g is None:
                continue
            if self.is_expert_param_func is not None and \
                    self.is_expert_param_func(p):
                expert.append((p, g))
            else:
                normal.append((p, g))
        return normal, expert

    # default pure clip_fn (no param identities): one global norm
    def clip_fn(self, grads):
        norm = jnp.sqrt(_sum_sq(grads))
        scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype)
                for g in grads]

    def __call__(self, params_grads):
        normal, expert = self._split(params_grads)
        norm_sq = _sum_sq([g._data if isinstance(g, Tensor) else g
                           for _, g in normal])
        expert_sq = _sum_sq([g._data if isinstance(g, Tensor) else g
                             for _, g in expert])
        # reference all-reduces expert_sq over moe_group; global-view
        # expert grads already include every expert, so it adds directly
        norm = jnp.sqrt(norm_sq + expert_sq)
        scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gd = g._data if isinstance(g, Tensor) else g
            out.append((p, Tensor._from_data(
                (gd.astype(jnp.float32) * scale).astype(gd.dtype))))
        return out
