"""incubate namespace completion (reference python/paddle/incubate/
__init__.py __all__): segment reductions, graph sampling, fused softmax
masks, optimizer wrappers."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "graph_khop_sampler", "graph_reindex",
           "graph_sample_neighbors", "identity_loss",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "LookAhead", "ModelAverage"]


def _d(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _segment(fn_name):
    def f(data, segment_ids, name=None):
        d, ids = _d(data), _d(segment_ids).astype(jnp.int32)
        n = int(np.asarray(ids).max()) + 1 if ids.size else 0
        fn = getattr(jax.ops, fn_name)
        return Tensor._from_data(fn(d, ids, num_segments=n))

    f.__name__ = fn_name
    return f


segment_sum = _segment("segment_sum")
segment_max = _segment("segment_max")
segment_min = _segment("segment_min")


def segment_mean(data, segment_ids, name=None):
    d, ids = _d(data), _d(segment_ids).astype(jnp.int32)
    n = int(np.asarray(ids).max()) + 1 if ids.size else 0
    s = jax.ops.segment_sum(d, ids, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                            num_segments=n)
    shape = (-1,) + (1,) * (d.ndim - 1)
    return Tensor._from_data(s / jnp.maximum(c.reshape(shape), 1))


def graph_send_recv(x, src_index, dst_index, reduce_op="sum",
                    out_size=None, name=None):
    from paddle_tpu.ops.registry import API

    return API["graph_send_recv"](x, src_index, dst_index,
                                  reduce_op=reduce_op.lower(),
                                  out_size=out_size or 0)


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           name=None):
    """Uniform neighbor sampling on a CSC graph (reference
    incubate/graph sampling ops — host-side there too)."""
    rows = np.asarray(_d(row))
    cp = np.asarray(_d(colptr))
    nodes = np.asarray(_d(input_nodes)).reshape(-1)
    out_n, out_count = [], []
    rng = np.random.default_rng()
    for v in nodes:
        nb = rows[cp[v]:cp[v + 1]]
        if sample_size > 0 and len(nb) > sample_size:
            nb = rng.choice(nb, sample_size, replace=False)
        out_n.append(nb)
        out_count.append(len(nb))
    flat = np.concatenate(out_n) if out_n else np.zeros((0,), rows.dtype)
    return (Tensor._from_data(jnp.asarray(flat)),
            Tensor._from_data(jnp.asarray(np.asarray(out_count,
                                                     np.int32))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling: iterate graph_sample_neighbors per hop."""
    frontier = np.asarray(_d(input_nodes)).reshape(-1)
    all_edges_src, all_edges_dst = [], []
    for k in (sample_sizes if isinstance(sample_sizes, (list, tuple))
              else [sample_sizes]):
        nbrs, counts = graph_sample_neighbors(row, colptr,
                                              jnp.asarray(frontier),
                                              sample_size=int(k))
        nb = np.asarray(nbrs._data)
        cnt = np.asarray(counts._data)
        dst = np.repeat(frontier, cnt)
        all_edges_src.append(nb)
        all_edges_dst.append(dst)
        frontier = np.unique(np.concatenate([frontier, nb]))
    src = np.concatenate(all_edges_src)
    dst = np.concatenate(all_edges_dst)
    r_src, r_dst, nodes = _reindex(np.asarray(_d(input_nodes)).reshape(-1),
                                   src, dst)
    return (Tensor._from_data(jnp.asarray(r_src)),
            Tensor._from_data(jnp.asarray(r_dst)),
            Tensor._from_data(jnp.asarray(nodes)),
            Tensor._from_data(jnp.asarray(
                np.arange(len(src), dtype=np.int64))))


def _reindex(seed_nodes, src, dst):
    nodes = np.concatenate([seed_nodes, src, dst])
    uniq = []
    seen = set()
    for v in nodes:
        if int(v) not in seen:
            seen.add(int(v))
            uniq.append(int(v))
    remap = {v: i for i, v in enumerate(uniq)}
    return (np.asarray([remap[int(v)] for v in src], np.int64),
            np.asarray([remap[int(v)] for v in dst], np.int64),
            np.asarray(uniq, np.int64))


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Reference graph_reindex: compact node ids to [0, N)."""
    seeds = np.asarray(_d(x)).reshape(-1)
    nb = np.asarray(_d(neighbors)).reshape(-1)
    cnt = np.asarray(_d(count)).reshape(-1)
    dst = np.repeat(seeds, cnt)
    r_src, r_dst, nodes = _reindex(seeds, nb, dst)
    return (Tensor._from_data(jnp.asarray(r_src)),
            Tensor._from_data(jnp.asarray(r_dst)),
            Tensor._from_data(jnp.asarray(nodes)))


def identity_loss(x, reduction="none"):
    """Reference incubate.identity_loss (IPU loss anchor): reduction of
    x itself."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 1):
        return x.sum()
    return x.mean()


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fusion region (reference fused CUDA
    kernel incubate/operators/softmax_mask_fuse.py)."""
    return Tensor._from_data(
        jax.nn.softmax(_d(x) + _d(mask), axis=-1))


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (upper triangle masked out)."""
    d = _d(x)
    s = d.shape[-1]
    mask = jnp.triu(jnp.full((s, s), -1e9, d.dtype), k=1)
    return Tensor._from_data(jax.nn.softmax(d + mask, axis=-1))


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate LookAhead):
    every k steps, slow weights move alpha toward the fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}
        self._n = 0

    def _params(self):
        return [p for p in (self.inner_optimizer._parameter_list or [])
                if not p.stop_gradient]

    def step(self):
        self.inner_optimizer.step()
        self._n += 1
        if self._n % self.k:
            return
        for p in self._params():
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._data
            slow = slow + self.alpha * (p._data - slow)
            self._slow[id(p)] = slow
            p._data = slow

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        return {"inner": getattr(self.inner_optimizer, "state_dict",
                                 dict)(), "n": self._n}


class ModelAverage:
    """Running parameter average applied at eval time (reference
    incubate ModelAverage): accumulate each step, apply()/restore()."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._sum = {}
        self._count = 0
        self._backup = {}

    def step(self):
        self._count += 1
        for p in self._params:
            self._sum[id(p)] = self._sum.get(id(p), 0.0) + p._data

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = {id(p): p._data for p in self._params}
            for p in self._params:
                if id(p) in self._sum and self._count:
                    p._data = self._sum[id(p)] / self._count
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}
