"""paddle.incubate.nn fused layers (reference:
incubate/nn/layer/fused_transformer.py — FusedMultiHeadAttention:189,
FusedFeedForward:483, FusedTransformerEncoderLayer:697 over handwritten
fused CUDA kernels).

TPU-native: the classes keep the reference's surface (pre/post
normalization knob, fused residual+dropout semantics) but emit plain
composed ops — XLA's fusion pass IS the fused kernel (the reference
needs hand-fused CUDA because its eager executor can't fuse across op
boundaries; a jitted step here fuses the whole block automatically).
"""
from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.layer import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedDropoutAdd", "FusedDropout", "FusedEcMoe",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiTransformer"]


class FusedMultiHeadAttention(Layer):
    """reference fused_transformer.py:189 — attention with fused
    qkv projection + residual + dropout + layernorm (pre or post)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-05,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.attn = nn.MultiHeadAttention(
            embed_dim, num_heads, dropout=attn_dropout_rate,
            kdim=kdim, vdim=vdim, need_weights=need_weights)
        self.dropout = nn.Dropout(dropout_rate)
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query
                 and value is not key):
            # the reference fused layer is self-attention only
            # (fused_transformer.py:189 "only support self attention")
            raise NotImplementedError(
                "FusedMultiHeadAttention supports self-attention only "
                "(matching the reference fused layer); use "
                "nn.MultiHeadAttention for cross-attention")
        residual = query
        if self.normalize_before:
            query = self.norm(query)
        key = value = query
        out = self.attn(query, key, value, attn_mask=attn_mask,
                        cache=cache)
        if cache is not None:
            out, new_cache = out
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        if cache is not None:
            return out, new_cache
        return out


class FusedFeedForward(Layer):
    """reference fused_transformer.py:483 — linear→act→dropout→linear
    with fused residual + layernorm."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward)
        self.linear2 = nn.Linear(dim_feedforward, d_model)
        self.dropout1 = nn.Dropout(act_dropout_rate
                                   if act_dropout_rate is not None
                                   else dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)
        self._act = getattr(paddle.nn.functional, activation)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.linear2(self.dropout1(self._act(self.linear1(src))))
        out = residual + self.dropout2(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """reference fused_transformer.py:697 — FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward,
                 dropout_rate=0.1, activation="relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate
            if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            out, new_cache = self.fused_attn(src, attn_mask=src_mask,
                                             cache=cache)
            return self.ffn(out), new_cache
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedLinear(Layer):
    """reference incubate/nn/layer/fused_linear.py — Linear whose bias
    add is a cuBLASLt epilogue there, an XLA fusion here. Init/attr
    handling mirrors nn.Linear (create_parameter honors
    weight_attr/bias_attr, bias_attr=False disables the bias)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True)
        self._transpose = transpose_weight

    def forward(self, x):
        from paddle_tpu.incubate.nn import functional as IF

        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """reference incubate/nn/layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from paddle_tpu.incubate.nn import functional as IF

        return IF.fused_dropout_add(x, y, p=self.p,
                                    training=self.training,
                                    mode=self.mode)


class FusedDropout(nn.Dropout):
    """reference incubate/nn/layer/fused_dropout_nd.py — identical
    semantics to nn.Dropout (axis-broadcast mask); alias kept for the
    reference's export set."""


class FusedEcMoe(Layer):
    """reference incubate/nn/layer/fused_ec_moe.py — dense
    expert-computation MoE over batched einsum (see functional)."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        import jax.numpy as jnp

        self.bmm0_weight = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm1_weight = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr)
        if bias_attr is False:
            # no bias parameters (reference contract); the functional
            # needs arrays, so constants of zeros stand in
            from paddle_tpu.core.tensor import Tensor

            self.bmm0_bias = Tensor._from_data(
                jnp.zeros((num_experts, 1, inter_size)))
            self.bmm1_bias = Tensor._from_data(
                jnp.zeros((num_experts, 1, hidden_size)))
        else:
            self.bmm0_bias = self.create_parameter(
                [num_experts, 1, inter_size], attr=bias_attr,
                is_bias=True)
            self.bmm1_bias = self.create_parameter(
                [num_experts, 1, hidden_size], attr=bias_attr,
                is_bias=True)
        self.act_type = act_type

    def forward(self, x, gate):
        from paddle_tpu.incubate.nn import functional as IF

        return IF.fused_ec_moe(x, gate, self.bmm0_weight, self.bmm0_bias,
                               self.bmm1_weight, self.bmm1_bias,
                               self.act_type)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as init

        if weight_attr is False:
            self.ln_scale = None
        else:
            self.ln_scale = self.create_parameter(
                [embed_dim], attr=weight_attr,
                default_initializer=init.Constant(1.0))
        if bias_attr is False:
            self.linear_bias = None
            self.ln_bias = None
        else:
            self.linear_bias = self.create_parameter(
                [embed_dim], attr=bias_attr, is_bias=True)
            self.ln_bias = self.create_parameter(
                [embed_dim], attr=bias_attr, is_bias=True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        from paddle_tpu.incubate.nn import functional as IF

        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedMultiTransformer(Layer):
    """reference incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer — a GPU serving mega-kernel stack; the
    TPU-native serving path is block_multihead_attention /
    masked_multihead_attention with XLA-fused layers."""

    def __init__(self, *args, **kwargs):
        super().__init__()
        raise NotImplementedError(
            "FusedMultiTransformer is a GPU serving mega-kernel; "
            "compose FusedTransformerEncoderLayer (training) or the "
            "serving attention ops (block/masked multihead attention) "
            "— XLA fuses the stack")
