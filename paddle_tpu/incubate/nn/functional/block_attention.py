"""Block / variable-length attention (serving-path attention variants).

Reference: python/paddle/incubate/nn/functional/
block_multihead_attention.py (paged KV-cache attention over fused CUDA
kernels) and variable_length_memory_efficient_attention.py (cutlass
memory-efficient varlen attention).

TPU-native redesign:
* ``variable_length_memory_efficient_attention`` — per-sequence length
  masking composed into one batched softmax-attention einsum; XLA fuses
  the mask+softmax+matmul chain (the "memory-efficient" part the
  reference gets from cutlass), and the long-sequence path is the
  Pallas flash kernel (ops/pallas/flash_attention.py).
* ``paged_attention`` / ``block_multihead_attention`` — the KV cache
  lives in fixed-size blocks indexed by a per-sequence block table
  (vLLM-style paging); block gathers are XLA dynamic-gathers and the
  attention math is batched on the MXU. Functional semantics: updated
  caches are RETURNED (the reference mutates them in place — in-place
  cache update on TPU is XLA buffer donation at the jit boundary).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["variable_length_memory_efficient_attention",
           "paged_attention", "block_multihead_attention",
           "ragged_paged_attention"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(d):
    return Tensor._from_data(d)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """query: (B, H, S, D); key/value: (B, KH, Sk, D) (KH may divide H —
    GQA broadcast); seq_lens/kv_seq_lens: (B,) or (B,1) valid lengths.
    Returns (B, H, S, D) with padding rows zeroed.

    Reference: variable_length_memory_efficient_attention.py (cutlass
    varlen kernel)."""
    q = _data(query)
    k = _data(key)
    v = _data(value)
    ql = _data(seq_lens).reshape(-1).astype(jnp.int32)
    kl = _data(kv_seq_lens).reshape(-1).astype(jnp.int32)
    b, h, s, d = q.shape
    kh = k.shape[1]
    if kh != h:
        rep = h // kh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    t = k.shape[2]
    q_pos = jnp.arange(s)[None, :]                    # (1, S)
    k_pos = jnp.arange(t)[None, :]                    # (1, Sk)
    q_valid = q_pos < ql[:, None]                     # (B, S)
    k_valid = k_pos < kl[:, None]                     # (B, Sk)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, logits.dtype)
    att_mask = k_valid[:, None, None, :]              # (B,1,1,Sk)
    if causal:
        causal_m = (jnp.arange(s)[:, None] + pre_cache_length
                    >= jnp.arange(t)[None, :])
        att_mask = att_mask & causal_m[None, None]
    logits = jnp.where(att_mask, logits, neg)
    if mask is not None:
        logits = logits + _data(mask).astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)
    return _wrap(out * q_valid[:, None, :, None].astype(out.dtype))


def paged_attention(q, key_cache, value_cache, block_tables, seq_lens,
                    scale=None):
    """Decode-phase attention over a paged KV cache.

    q: (B, H, D) — one new token per sequence;
    key_cache/value_cache: (num_blocks, block_size, KH, D);
    block_tables: (B, max_blocks) int32 physical-block ids (-1 pads);
    seq_lens: (B,) tokens already in cache (including the new one).
    Returns (B, H, D)."""
    qd = _data(q)
    kc = _data(key_cache)
    vc = _data(value_cache)
    bt = _data(block_tables).astype(jnp.int32)
    sl = _data(seq_lens).reshape(-1).astype(jnp.int32)
    b, h, d = qd.shape
    nb, bs, kh, _ = kc.shape
    mb = bt.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    safe_bt = jnp.maximum(bt, 0)
    # gather each sequence's blocks: (B, mb, bs, KH, D) -> (B, T, KH, D)
    k_seq = kc[safe_bt].reshape(b, mb * bs, kh, d)
    v_seq = vc[safe_bt].reshape(b, mb * bs, kh, d)
    if kh != h:
        rep = h // kh
        k_seq = jnp.repeat(k_seq, rep, axis=2)
        v_seq = jnp.repeat(v_seq, rep, axis=2)
    logits = jnp.einsum("bhd,bthd->bht", qd, k_seq) * scale
    pos = jnp.arange(mb * bs)[None, :]
    valid = (pos < sl[:, None]) & (bt >= 0).repeat(bs, axis=1)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, logits.dtype)
    logits = jnp.where(valid[:, None, :], logits, neg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return _wrap(jnp.einsum("bht,bthd->bhd", probs.astype(v_seq.dtype),
                            v_seq))


def ragged_paged_attention(q, k_new, v_new, key_cache, value_cache,
                           block_tables, cu_seqlens, context_lens,
                           num_seqs, scale=None):
    """Unpadded prefill+decode attention over a concatenated token stream
    (ops/pallas/ragged_paged_attention.py; arxiv 2604.15464). q/k_new/
    v_new: (T, H|KH, D) ragged-packed rows; cu_seqlens (S+1,) delimits
    sequence slots, context_lens (S,) is the post-step cache length per
    slot, block_tables (S, MB) the paged-cache indirection. Returns
    (out (T, H, D), key_cache', value_cache') — caches are returned, not
    mutated (in-place on TPU is buffer donation at the jit boundary)."""
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention as _rpa)

    out, kc, vc = _rpa(
        _data(q), _data(k_new), _data(v_new), _data(key_cache),
        _data(value_cache), _data(block_tables), _data(cu_seqlens),
        _data(context_lens), _data(num_seqs), scale=scale)
    return _wrap(out), _wrap(kc), _wrap(vc)


def _write_cache(cache, blocks, block_tables, positions):
    """Scatter new K/V rows into their paged slots. positions: (B, S)
    absolute token positions (-1 = skip); blocks: (B, S, KH, D)."""
    bt = block_tables
    bs = cache.shape[1]
    blk = jnp.where(positions >= 0, positions // bs, 0)
    off = jnp.where(positions >= 0, positions % bs, 0)
    phys = jnp.take_along_axis(jnp.maximum(bt, 0), blk, axis=1)
    # a position whose block-table entry is -1 (unallocated block) must be
    # dropped, not routed through max(bt,0) into physical block 0 where it
    # would clobber real cached tokens
    entry = jnp.take_along_axis(bt, blk, axis=1)
    valid = (positions >= 0) & (entry >= 0)
    flat_idx = phys * bs + off                     # (B, S)
    cache_flat = cache.reshape(-1, *cache.shape[2:])
    upd = blocks.reshape(-1, *blocks.shape[2:])
    n_slots = cache_flat.shape[0]
    # padded rows scatter to an out-of-range index and are DROPPED —
    # routing them to slot 0 would clobber the real token-0 write when
    # duplicate indices resolve against us
    fi = jnp.where(valid.reshape(-1), flat_idx.reshape(-1), n_slots)
    cache_flat = cache_flat.at[fi].set(upd, mode="drop")
    return cache_flat.reshape(cache.shape)


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, block_tables, max_seq_len=None,
        block_size=None, pre_key_cache=None, pre_value_cache=None,
        rope_emb=None, mask=None, causal=True, num_heads=None,
        kv_num_heads=None, head_dim=None,
        tp_degree=1) -> Tuple[Tensor, Tensor, Tensor]:
    """Unified prefill/decode attention over a paged KV cache
    (reference block_multihead_attention.py; the vLLM-style serving
    attention). Two modes per sequence, chosen by the length tensors:

    * prefill (seq_lens_encoder[b] > 0): the b-th sequence's S new
      tokens attend causally among themselves; their K/V are written
      into the paged cache.
    * decode (seq_lens_decoder[b] > 0): one new token attends to the
      whole cached prefix + itself.

    qkv: (B, S, 3, H, D) packed (padded to the longest sequence this
    step); returns (out (B, S, H, D), key_cache', value_cache').
    Divergence (documented): caches are returned, not mutated; the
    reference's int8/cachekv-quant variants ride the quantization
    module instead."""
    if rope_emb is not None or pre_key_cache is not None or \
            pre_value_cache is not None:
        raise NotImplementedError(
            "block_multihead_attention: rope_emb / pre_key_cache / "
            "pre_value_cache are not applied in this build — apply "
            "rotary embeddings to qkv before the call "
            "(incubate.nn.functional.fused_rotary_position_embedding) "
            "and fold any prefix cache into key_cache/value_cache")
    qkvd = _data(qkv)
    kc = _data(key_cache)
    vc = _data(value_cache)
    bt = _data(block_tables).astype(jnp.int32)
    enc = _data(seq_lens_encoder).reshape(-1).astype(jnp.int32)
    dec = _data(seq_lens_decoder).reshape(-1).astype(jnp.int32)
    now = _data(seq_lens_this_time).reshape(-1).astype(jnp.int32)
    b, s, three, h, d = qkvd.shape
    kh = kc.shape[2]
    bs = kc.shape[1] if block_size is None else block_size
    q = qkvd[:, :, 0]
    # qkv carries H heads per slot (the caller unpacks (H+2*KH)-wide
    # fused projections); GQA keeps the first kh K/V heads. Under
    # tensor parallelism the caller packs per TP head group — each
    # group's KH/tp kv heads lead its H/tp q-head slots — so this
    # unpack never crosses a head-dim shard boundary.
    tp = max(1, int(tp_degree))
    if tp > 1:
        grp = qkvd.reshape(b, s, three, tp, h // tp, d)
        k_new = grp[:, :, 1, :, :kh // tp].reshape(b, s, kh, d)
        v_new = grp[:, :, 2, :, :kh // tp].reshape(b, s, kh, d)
    else:
        k_new = qkvd[:, :, 1, :kh]
        v_new = qkvd[:, :, 2, :kh]

    # write new K/V into the cache at [start, start+now) where start is
    # the already-cached prefix (decode) or 0 (prefill)
    start = jnp.where(dec > 0, dec, 0)
    pos = start[:, None] + jnp.arange(s)[None, :]
    pos = jnp.where(jnp.arange(s)[None, :] < now[:, None], pos, -1)
    kc = _write_cache(kc, k_new, bt, pos)
    vc = _write_cache(vc, v_new, bt, pos)

    # attention against the updated cache: every query token at
    # absolute position p attends to cache positions <= p (causal)
    total = jnp.where(dec > 0, dec + now, now)      # (B,) tokens valid
    mb = bt.shape[1]
    safe_bt = jnp.maximum(bt, 0)
    k_seq = kc[safe_bt].reshape(b, mb * bs, kh, d)
    v_seq = vc[safe_bt].reshape(b, mb * bs, kh, d)
    if kh != h:
        rep = h // kh
        k_seq = jnp.repeat(k_seq, rep, axis=2)
        v_seq = jnp.repeat(v_seq, rep, axis=2)
    scale = 1.0 / (d ** 0.5)
    logits = jnp.einsum("bshd,bthd->bhst", q, k_seq) * scale
    t = mb * bs
    tpos = jnp.arange(t)[None, :]
    cache_valid = (tpos < total[:, None]) & (bt >= 0).repeat(bs, axis=1)
    att = cache_valid[:, None, None, :]
    if causal:
        qpos = pos  # (B, S) absolute positions (-1 pad)
        cm = qpos[:, None, :, None] >= tpos[:, None, None, :]
        att = att & cm
    if mask is not None:
        logits = logits + _data(mask).astype(logits.dtype)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, logits.dtype)
    logits = jnp.where(att, logits, neg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs.astype(v_seq.dtype), v_seq)
    q_valid = (jnp.arange(s)[None, :] < now[:, None])
    out = out * q_valid[:, :, None, None].astype(out.dtype)
    return _wrap(out), _wrap(kc), _wrap(vc)
