"""Fused-op python APIs.

Reference: python/paddle/incubate/nn/functional/
(fused_rotary_position_embedding.py, fused_rms_norm.py, swiglu.py).
On TPU "fused" means: expressed as one registry op whose body XLA fuses
into neighboring matmuls — no custom kernel needed for these
bandwidth-bound elementwise chains.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops import registry as _registry
from paddle_tpu.ops.registry import register_emitter as _register

from paddle_tpu.incubate.nn.functional.block_attention import (  # noqa: F401
    block_multihead_attention, paged_attention, ragged_paged_attention,
    variable_length_memory_efficient_attention,
)

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm", "swiglu",
           "variable_length_memory_efficient_attention",
           "paged_attention", "block_multihead_attention",
           "ragged_paged_attention"]


@_register(name="swiglu")
def _swiglu_emitter(x, y=None):
    """silu(x) * y; with y=None, x is split in half on the last axis
    (reference swiglu.py semantics)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@_register(name="fused_rms_norm")
def _fused_rms_norm_emitter(x, norm_weight, norm_bias=None, epsilon=1e-6,
                            begin_norm_axis=-1):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype) * norm_weight
    if norm_bias is not None:
        out = out + norm_bias
    return out


if "swiglu" not in _registry.OPS:
    _registry.build_registry([
        {"op": "swiglu", "tensor_args": ["x", "y"], "methods": []},
        {"op": "fused_rms_norm",
         "tensor_args": ["x", "norm_weight", "norm_bias"], "methods": []},
    ])


def swiglu(x, y=None):
    return _registry.API["swiglu"](x, y)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    return _registry.API["fused_rms_norm"](x, norm_weight, norm_bias,
                                           epsilon=epsilon,
                                           begin_norm_axis=begin_norm_axis)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """Rotary embedding on [B, S, H, D] tensors (reference
    fused_rotary_position_embedding.py). Reuses the rope_apply op the
    Llama model registers; v passes through rotated like k when given."""
    from paddle_tpu.models import llama  # registers rope_apply  # noqa

    if cos is None or sin is None:
        raise ValueError("cos/sin tables are required")
    # tables may be [S, D] or [1, S, 1, D]
    def squeeze(t):
        d = t._data if hasattr(t, "_data") else t
        return t.reshape([d.shape[-3], d.shape[-1]]) if d.ndim == 4 else t

    cos2, sin2 = squeeze(cos), squeeze(sin)
    if k is None:
        q2, _ = _registry.API["rope_apply"](q, q, cos2, sin2)
        return q2, None, None
    q2, k2 = _registry.API["rope_apply"](q, k, cos2, sin2)
    v2 = v
    return q2, k2, v2


from paddle_tpu.incubate.nn.functional.fused_ops import (  # noqa: E402,F401
    fused_bias_dropout_residual_layer_norm, fused_dot_product_attention,
    fused_dropout_add, fused_ec_moe, fused_feedforward, fused_gate_attention,
    fused_layer_norm, fused_linear, fused_linear_activation,
    fused_matmul_bias, fused_multi_head_attention, fused_multi_transformer,
    masked_multihead_attention,
)
