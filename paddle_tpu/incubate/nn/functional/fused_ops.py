"""Fused-op functional surface (reference:
python/paddle/incubate/nn/functional/ — fused_layer_norm.py,
fused_dropout_add.py, fused_matmul_bias.py, fused_dot_product_attention
.py, fused_ec_moe.py, masked_multihead_attention.py,
fused_transformer.py).

TPU-native stance: the reference ships these as handwritten CUDA
mega-kernels because CUDA cannot fuse across launches; under XLA the
SAME compositions fuse automatically, so each function here is the
reference's documented pseudo-code written over registry ops — one
compiled fusion region, zero custom kernels, full autograd. The two
GPU-serving-specific variants whose value is a bespoke decode kernel
(fused_multi_transformer, fused_gate_attention) raise with a pointer at
the TPU-native serving path (block_multihead_attention / paged cache).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import registry as _registry

__all__ = [
    "fused_layer_norm", "fused_dropout_add", "fused_matmul_bias",
    "fused_linear", "fused_linear_activation",
    "fused_dot_product_attention", "fused_ec_moe",
    "masked_multihead_attention", "fused_bias_dropout_residual_layer_norm",
    "fused_feedforward", "fused_multi_head_attention",
    "fused_multi_transformer", "fused_gate_attention",
]


def _d(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _act(name):
    from paddle_tpu.nn import functional as F

    acts = {"relu": F.relu, "gelu": F.gelu}
    if name not in acts:
        raise ValueError(
            f"unsupported activation {name!r} (relu|gelu; geglu needs a "
            "split+gate projection — compose it explicitly)")
    return acts[name]


def fused_layer_norm(x, norm_weight, norm_bias, epsilon,
                     residual_alpha=1.0, begin_norm_axis=1, bias=None,
                     residual=None, quant_scale=-1, quant_round_type=0,
                     quant_max_bound=0, quant_min_bound=0):
    """LayerNorm(bias + residual_alpha*residual + x) fused pattern
    (reference fused_layer_norm.py:21); norm_weight=None returns just
    the fused add."""
    if quant_scale != -1:
        raise NotImplementedError(
            "quantized fused_layer_norm: use paddle_tpu.quantization "
            "(int8 export) instead")
    y = x
    if bias is not None:
        y = y + bias
    if residual is not None:
        y = y + residual * residual_alpha
    if norm_weight is None and norm_bias is None:
        return y
    from paddle_tpu.nn import functional as F

    d = _d(y)
    axes = tuple(range(begin_norm_axis if begin_norm_axis >= 0
                       else d.ndim + begin_norm_axis, d.ndim))
    import math

    shape = [d.shape[a] for a in axes]
    flat_shape = [math.prod(shape)]
    return F.layer_norm(
        y.reshape(list(d.shape[:axes[0]]) + flat_shape),
        normalized_shape=flat_shape,
        weight=norm_weight.reshape(flat_shape)
        if norm_weight is not None else None,
        bias=norm_bias.reshape(flat_shape)
        if norm_bias is not None else None,
        epsilon=epsilon).reshape(list(d.shape))


def fused_dropout_add(x, y, p=0.5, training=True,
                      mode="upscale_in_train", name=None):
    """dropout(x) + y (reference fused_dropout_add.py:22)."""
    from paddle_tpu.nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """matmul + bias epilogue (reference fused_matmul_bias.py:24 —
    cuBLASLt epilogue there; one XLA fusion here)."""
    out = _registry.API["matmul"](x, y, transpose_x=transpose_x,
                                  transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False,
                 name=None):
    """Reference fused_matmul_bias.py:83."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False,
                            trans_y=False, activation=None):
    """matmul + bias + activation epilogue (reference
    fused_matmul_bias.py fused_linear_activation)."""
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    if activation in (None, "none"):
        return out
    return _act(activation)(out)


def fused_dot_product_attention(q, k, v, mask=None, scaling_factor=None,
                                dropout_prob=0.0, is_training=True,
                                is_causal_masking=False,
                                use_workspace_opt=None,
                                return_softmax=False):
    """Reference fused_dot_product_attention.py:22 (cuDNN fused
    attention there; the registry's scaled_dot_product_attention — the
    XLA/Pallas path — here). q/k/v: [B, S, H, D]."""
    if return_softmax:
        raise NotImplementedError(
            "return_softmax=True keeps the full [B,H,S,S] matrix alive "
            "— incompatible with flash-style attention; compute softmax "
            "explicitly if you need it")
    if is_causal_masking and mask is not None:
        raise NotImplementedError(
            "combined causal + explicit mask is not supported: fold the "
            "causal structure into the mask and pass "
            "is_causal_masking=False")
    from paddle_tpu.nn import functional as F

    q_ = q
    if scaling_factor is not None:
        # SDPA scales by 1/sqrt(D) internally: pre-scale q so the
        # effective scale is the caller's scaling_factor
        import math

        D = _d(q).shape[-1]
        q_ = q * (float(scaling_factor) * math.sqrt(D))
    out = F.scaled_dot_product_attention(
        q_, k, v, attn_mask=mask,
        dropout_p=dropout_prob if is_training else 0.0,
        is_causal=is_causal_masking, training=is_training)
    return out


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                 bmm1_bias, act_type):
    """Dense expert-computation MoE (reference fused_ec_moe.py:18):
    out[b,s] = sum_e softmax(gate)[b,s,e] *
               (act(x @ W0_e + b0_e) @ W1_e + b1_e).
    Every token runs every expert as batched einsum — the MXU-dense
    formulation (the reference's grouped GEMM plays the same trick on
    tensor cores)."""
    if act_type not in ("gelu", "relu"):
        raise ValueError("act_type must be 'gelu' or 'relu'")
    xd, gd = _d(x), _d(gate)
    w0, b0 = _d(bmm0_weight), _d(bmm0_bias)
    w1, b1 = _d(bmm1_weight), _d(bmm1_bias)
    probs = jax.nn.softmax(gd, axis=-1)                  # [B, S, E]
    h = jnp.einsum("bsd,edf->bsef", xd, w0) + b0[:, 0]   # [B, S, E, F]
    h = jax.nn.gelu(h) if act_type == "gelu" else jnp.maximum(h, 0)
    E_, F_ = w0.shape[0], w0.shape[2]
    D_ = xd.shape[-1]
    if w1.shape != (E_, F_, D_):
        raise ValueError(
            f"bmm1_weight must be [num_experts, d_ffn, d_model] = "
            f"[{E_}, {F_}, {D_}], got {tuple(w1.shape)} (the reference "
            "docstring's [e, d_model, d_ffn] is inconsistent with the "
            "kernel's contraction; layout sniffing would silently "
            "misinterpret square FFNs)")
    y = jnp.einsum("bsef,efd->bsed", h, w1)
    y = y + b1[:, 0]
    out = jnp.einsum("bsed,bse->bsd", y, probs)
    return Tensor._from_data(out)


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, cum_offsets=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Single-token decode attention over a dense KV cache (reference
    masked_multihead_attention.py:19). x: [B, 3*H*D] packed qkv;
    cache_kv: [2, B, H, max_seq, D]; sequence_lengths: [B] current
    lengths (defaults to the cache's full prefix). Returns
    (out [B, H*D], updated cache) — functional cache update, the
    jit-safe TPU idiom (in-place KV writes have no XLA analog)."""
    for unsupported, nm in ((beam_cache_offset, "beam search"),
                            (qkv_out_scale, "quantized qkv"),
                            (out_shift, "out_shift"),
                            (rotary_tensor, "rotary_tensor")):
        if unsupported is not None:
            raise NotImplementedError(
                f"masked_multihead_attention: {nm} is not supported; "
                "use incubate block_multihead_attention for the paged "
                "serving path")
    xd = _d(x)
    cache = _d(cache_kv)
    _, B, H, S_max, D = cache.shape
    qkv = xd.reshape(B, 3, H, D)
    if bias is not None:
        qkv = qkv + _d(bias).reshape(1, 3, H, D)
    q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]     # [B, H, D]
    if sequence_lengths is None:
        lens = jnp.full((B,), S_max - 1, jnp.int32)
    else:
        lens = _d(sequence_lengths).reshape(B).astype(jnp.int32)
        try:  # eager (concrete) path: catch cache overflow loudly
            import numpy as _np

            if int(_np.asarray(lens).max()) >= S_max:
                raise ValueError(
                    f"masked_multihead_attention: sequence length "
                    f"{int(_np.asarray(lens).max())} has no free cache "
                    f"slot (max_seq={S_max}); grow the cache")
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            pass  # traced: caller owns the bound
    # append this step's k/v at position lens[b]
    onehot = jax.nn.one_hot(lens, S_max, dtype=cache.dtype)  # [B, S]
    k_cache = cache[0] * (1 - onehot[:, None, :, None]) + \
        k_new[:, :, None, :] * onehot[:, None, :, None]
    v_cache = cache[1] * (1 - onehot[:, None, :, None]) + \
        v_new[:, :, None, :] * onehot[:, None, :, None]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    logits = jnp.einsum("bhd,bhsd->bhs", q, k_cache) * scale
    pos = jnp.arange(S_max)[None, :]
    valid = pos <= lens[:, None]                           # [B, S]
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, logits.dtype)
    logits = jnp.where(valid[:, None, :], logits, neg)
    if src_mask is not None:
        sm = _d(src_mask).reshape(B, 1, -1)[:, :, :S_max]
        logits = logits + sm
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhs,bhsd->bhd", probs.astype(v_cache.dtype),
                     v_cache)
    new_cache = jnp.stack([k_cache, v_cache])
    return (Tensor._from_data(out.reshape(B, H * D)),
            Tensor._from_data(new_cache))


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """layer_norm(residual + dropout(bias + x)) (reference
    fused_transformer.py:323)."""
    from paddle_tpu.nn import functional as F

    y = x if bias is None else x + bias
    y = residual + F.dropout(y, p=dropout_rate, training=training,
                             mode=mode)
    d = _d(y)
    return F.layer_norm(y, normalized_shape=[d.shape[-1]],
                        weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """residual + dropout2(linear2(dropout1(act(linear1(ln?(x))))))
    (reference fused_transformer.py:36)."""
    from paddle_tpu.nn import functional as F

    residual = x
    d = _d(x)
    y = x
    if pre_layer_norm:
        y = F.layer_norm(y, normalized_shape=[d.shape[-1]],
                         weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    y = _act(activation)(F.linear(y, linear1_weight, linear1_bias))
    y = F.dropout(y, p=dropout1_rate, training=training, mode=mode)
    y = F.linear(y, linear2_weight, linear2_bias)
    y = F.dropout(y, p=dropout2_rate, training=training, mode=mode)
    if add_residual:
        y = y + residual
    if not pre_layer_norm:
        y = F.layer_norm(y, normalized_shape=[d.shape[-1]],
                         weight=ln2_scale, bias=ln2_bias,
                         epsilon=ln2_epsilon)
    return y


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Self-attention block (reference fused_transformer.py:502):
    ln? -> qkv -> attention -> out proj -> bias+dropout+residual+ln?.
    qkv_weight: [3, H, D, embed] (paddle layout) or [embed, 3*embed]
    with transpose_qkv_wb=True + num_heads."""
    from paddle_tpu.nn import functional as F

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention with cache_kv: use "
            "masked_multihead_attention (dense decode cache) or "
            "block_multihead_attention (paged)")
    residual = x
    d = _d(x)
    B, S, E = d.shape
    y = x
    if pre_layer_norm:
        y = F.layer_norm(y, normalized_shape=[E], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    wd = _d(qkv_weight)
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("num_heads required with transpose_qkv_wb")
        H = num_heads
        D = E // H
        qkv = jnp.einsum("bse,ek->bsk", _d(y), wd)
        if qkv_bias is not None:
            qkv = qkv + _d(qkv_bias)
        qkv = qkv.reshape(B, S, 3, H, D)
    else:
        _, H, D, _ = wd.shape
        qkv = jnp.einsum("bse,khde->bskhd", _d(y), wd)
        if qkv_bias is not None:
            qkv = qkv + _d(qkv_bias)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, S, H, D]
    out = F.scaled_dot_product_attention(
        Tensor._from_data(q), Tensor._from_data(k),
        Tensor._from_data(v), attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = _d(out).reshape(B, S, H * D)
    out = jnp.matmul(out, _d(linear_weight))
    y = Tensor._from_data(out)
    if pre_layer_norm:
        return _post_pre_ln(y, linear_bias, residual, dropout_rate,
                            training, mode, add_residual)
    return fused_bias_dropout_residual_layer_norm(
        y, residual if add_residual else y * 0.0, bias=linear_bias,
        ln_scale=ln_scale, ln_bias=ln_bias, dropout_rate=dropout_rate,
        ln_epsilon=ln_epsilon, training=training, mode=mode)


def _post_pre_ln(y, linear_bias, residual, dropout_rate, training, mode,
                 add_residual):
    """pre_layer_norm epilogue: bias + dropout + residual (no final ln)."""
    from paddle_tpu.nn import functional as F

    if linear_bias is not None:
        y = y + linear_bias
    y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        y = y + residual
    return y


def fused_multi_transformer(*args, **kwargs):
    raise NotImplementedError(
        "fused_multi_transformer is a GPU serving mega-kernel; the "
        "TPU-native serving path is incubate block_multihead_attention "
        "(paged KV cache) / masked_multihead_attention (dense decode), "
        "with layers compiled and fused by XLA — see "
        "paddle_tpu.incubate.nn.FusedTransformerEncoderLayer")


def fused_gate_attention(*args, **kwargs):
    raise NotImplementedError(
        "fused_gate_attention (AlphaFold gating) is not implemented; "
        "compose it from scaled_dot_product_attention + sigmoid gating "
        "— XLA fuses the composition into one kernel region")
