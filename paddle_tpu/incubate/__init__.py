"""incubate — fused / experimental APIs.

Reference: python/paddle/incubate/ (nn/functional fused ops, MoE under
incubate/distributed/models/moe)."""
from paddle_tpu.incubate import moe  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401
