"""incubate — fused / experimental APIs.

Reference: python/paddle/incubate/ (nn/functional fused ops, MoE under
incubate/distributed/models/moe)."""
from paddle_tpu.incubate import moe  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate.compat import (  # noqa: F401
    LookAhead, ModelAverage, graph_khop_sampler, graph_reindex,
    graph_sample_neighbors, graph_send_recv, identity_loss, segment_max,
    segment_mean, segment_min, segment_sum, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
