"""ASP: automatic n:m structured sparsity (2:4 by default).

Reference: python/paddle/incubate/asp/asp.py — ``prune_model`` (:302)
computes an n:m mask per supported weight via mask_1d/mask_2d_greedy/
mask_2d_best (supported_layer_list.py, utils.py), ``decorate`` (:216)
wraps the optimizer so every step re-applies the masks
(OptimizerWithSparsityGuarantee), and set/reset_excluded_layers scope
which layers participate.

TPU-native: the mask lives as a dense 0/1 array multiplied into the
weight after every optimizer update — inside compiled train steps the
multiply fuses into the update kernel (XLA), which is the whole
enforcement cost; there is no sparse-tensor-core kernel to dispatch to
(the MXU has no 2:4 mode), so the win on TPU is model compression +
mask-pattern parity with the reference's Ampere workflow. Mask math is
computed host-side in numpy at prune time (offline, like the
reference's CPU mask generation).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

__all__ = ["prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "create_mask", "check_sparsity"]

_excluded_param_names: set = set()


def set_excluded_layers(param_names, main_program=None):
    """Exclude parameters (by name) from pruning (reference asp.py:40)."""
    _excluded_param_names.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded_param_names.clear()


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.calculate_density)."""
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


# ---- mask generation (reference incubate/asp/utils.py) --------------------
def _mask_1d(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest |values| in every contiguous group of m along
    the last axis."""
    groups = mat.reshape(-1, m)
    order = np.argsort(-np.abs(groups), axis=1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(mat.shape)


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m 0/1 blocks with exactly n ones per row AND per column
    (reference utils.compute_valid_2d_patterns)."""
    import itertools

    rows = [np.array(p) for p in itertools.product([0, 1], repeat=m)
            if sum(p) == n]
    pats = []
    for combo in itertools.product(range(len(rows)), repeat=m):
        block = np.stack([rows[i] for i in combo])
        if (block.sum(0) == n).all():
            pats.append(block)
    return np.stack(pats)  # [P, m, m]


def _mask_2d(mat: np.ndarray, n: int, m: int, best: bool) -> np.ndarray:
    """n:m in BOTH dimensions on m x m blocks. ``best`` scores every
    valid pattern (reference mask_2d_best); greedy evaluates patterns on
    the magnitude-sorted subset (here: same exhaustive scoring — m=4 has
    only 90 valid patterns, so 'greedy' needs no approximation)."""
    h, w = mat.shape
    if h % m or w % m:
        raise ValueError(f"mask_2d needs dims divisible by {m}: {mat.shape}")
    pats = _valid_2d_patterns(n, m)  # [P, m, m]
    blocks = np.abs(
        mat.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3))
    # score every pattern on every block, take argmax
    scores = np.einsum("abij,pij->abp", blocks, pats)
    choice = scores.argmax(-1)  # [h/m, w/m]
    mask_blocks = pats[choice]  # [h/m, w/m, m, m]
    return mask_blocks.transpose(0, 2, 1, 3).reshape(h, w)


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2,
                m: int = 4) -> np.ndarray:
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy")
                     else tensor, dtype=np.float32)
    shape = arr.shape
    mat2d = arr.reshape(shape[0], -1) if arr.ndim != 2 else arr
    if func_name == "mask_1d":
        mask = _mask_1d(mat2d, n, m)
    elif func_name == "mask_2d_greedy":
        mask = _mask_2d(mat2d, n, m, best=False)
    elif func_name == "mask_2d_best":
        mask = _mask_2d(mat2d, n, m, best=True)
    else:
        raise ValueError(f"unknown mask_algo {func_name!r}")
    return mask.reshape(shape)


def check_sparsity(tensor, n: int = 2, m: int = 4,
                   func_name: str = "mask_1d") -> bool:
    """Does the tensor satisfy the n:m pattern (reference
    utils.check_sparsity)?"""
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy")
                     else tensor)
    mat = arr.reshape(arr.shape[0], -1) if arr.ndim != 2 else arr
    if func_name == "mask_1d":
        if mat.size % m:
            return False
        groups = (mat.reshape(-1, m) != 0).sum(1)
        return bool((groups <= n).all())
    nz = (mat != 0)
    h, w = mat.shape
    blocks = nz.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3)
    return bool((blocks.sum(2) <= n).all() and (blocks.sum(3) <= n).all())


# ---- pruning + enforcement -------------------------------------------------
def _supported_params(model):
    """Weights of Linear/Conv layers with m-divisible reduce dims
    (reference _is_supported_layer + supported_layer_list)."""
    from paddle_tpu import nn

    out = []
    for lname, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, (nn.Linear, nn.Conv2D)):
            continue
        w = getattr(layer, "weight", None)
        if w is None or w._data.ndim < 2:
            continue
        pname = f"{lname}.weight" if lname else "weight"
        if pname in _excluded_param_names or \
                getattr(w, "name", None) in _excluded_param_names:
            continue
        out.append((pname, w))
    return out


class _MaskRegistry(dict):
    """id(param) -> (weakref(param), mask). ``get`` validates the param
    is still alive before returning its mask: a plain id-keyed dict
    would leak masks forever AND could hand a dead param's mask to an
    unrelated tensor whose CPython id recycled the slot."""

    def register(self, param, mask):
        import weakref

        dict.__setitem__(self, id(param), (weakref.ref(param), mask))

    def get(self, pid, default=None):
        ent = dict.get(self, pid)
        if ent is None:
            return default
        wref, mask = ent
        if wref() is None:
            del self[pid]
            return default
        return mask


# global mask registry. decorate() hands the SAME object to the
# optimizer, so decorate/prune order is free (the reference requires
# decorate-before-prune; this relaxes it).
_PARAM_MASKS = _MaskRegistry()


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True) -> Dict[str, np.ndarray]:
    """Prune supported weights to the n:m pattern in place; when
    ``with_mask`` the masks are registered so a decorated optimizer
    keeps enforcing them after every update (reference asp.py:302)."""
    masks: Dict[str, np.ndarray] = {}
    for pname, w in _supported_params(model):
        flat = w._data.reshape(w._data.shape[0], -1) \
            if w._data.ndim != 2 else w._data
        if flat.shape[-1] % m:
            continue
        mask = create_mask(w, mask_algo, n, m)
        w._data = w._data * jnp.asarray(mask, w._data.dtype)
        masks[pname] = mask
        if with_mask:
            _PARAM_MASKS.register(w, jnp.asarray(mask))
    model._asp_masks = masks
    return masks


def decorate(optimizer):
    """ASP-enable the optimizer (reference asp.py:216
    OptimizerWithSparsityGuarantee): every parameter update re-applies
    its registered mask — in eager ``step()`` and inside compiled
    TrainSteps alike (Optimizer._rule_mp multiplies ``_param_masks``
    entries into the updated weight; XLA fuses the multiply into the
    update)."""
    optimizer._param_masks = _PARAM_MASKS
    return optimizer
