"""paddle.fft — spectral transforms (reference: python/paddle/fft.py).

The reference dispatches to pocketfft (CPU) / cuFFT (GPU) through phi
fft kernels (paddle/phi/kernels/funcs/fft.cc); here every transform is
one registry op lowering to the XLA Fft HLO, differentiable through the
standard vjp path.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import API as _API

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _make(name):
    fn = _API[name]

    def wrapper(x, *a, **k):
        return fn(x, *a, **k)

    wrapper.__name__ = name
    return wrapper


fft = _make("fft")
ifft = _make("ifft")
fft2 = _make("fft2")
ifft2 = _make("ifft2")
fftn = _make("fftn")
ifftn = _make("ifftn")
rfft = _make("rfft")
irfft = _make("irfft")
rfft2 = _make("rfft2")
irfft2 = _make("irfft2")
rfftn = _make("rfftn")
irfftn = _make("irfftn")
hfft = _make("hfft")
ihfft = _make("ihfft")
fftshift = _make("fftshift")
ifftshift = _make("ifftshift")


def fftfreq(n, d=1.0, dtype="float32"):
    return Tensor(np.fft.fftfreq(int(n), d=float(d)), dtype=dtype)


def rfftfreq(n, d=1.0, dtype="float32"):
    return Tensor(np.fft.rfftfreq(int(n), d=float(d)), dtype=dtype)
