"""paddle.fft — spectral transforms (reference: python/paddle/fft.py).

The reference dispatches to pocketfft (CPU) / cuFFT (GPU) through phi
fft kernels (paddle/phi/kernels/funcs/fft.cc); here every transform is
one registry op lowering to the XLA Fft HLO, differentiable through the
standard vjp path.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import API as _API

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


# the registry ops ARE the public functions
fft = _API["fft"]
ifft = _API["ifft"]
fft2 = _API["fft2"]
ifft2 = _API["ifft2"]
fftn = _API["fftn"]
ifftn = _API["ifftn"]
rfft = _API["rfft"]
irfft = _API["irfft"]
rfft2 = _API["rfft2"]
irfft2 = _API["irfft2"]
rfftn = _API["rfftn"]
irfftn = _API["irfftn"]
hfft = _API["hfft"]
ihfft = _API["ihfft"]
fftshift = _API["fftshift"]
ifftshift = _API["ifftshift"]


def fftfreq(n, d=1.0, dtype="float32"):
    return Tensor(np.fft.fftfreq(int(n), d=float(d)), dtype=dtype)


def rfftfreq(n, d=1.0, dtype="float32"):
    return Tensor(np.fft.rfftfreq(int(n), d=float(d)), dtype=dtype)
