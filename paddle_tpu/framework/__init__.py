"""Framework-level utilities (reference: python/paddle/framework/)."""
from paddle_tpu.framework.io_utils import load, save  # noqa: F401
from paddle_tpu.framework.param_attr import ParamAttr  # noqa: F401
