"""paddle.save / paddle.load parity.

Reference: python/paddle/framework/io.py:725 (save), :967 (load) — pickled
state_dict of params + optimizer state. Tensors are stored as numpy arrays
(bf16 stored as uint16 view with a dtype tag).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["save", "load"]

_BF16_TAG = "__bf16__"


def _to_picklable(obj):
    import jax.numpy as jnp

    if isinstance(obj, Tensor):
        d = obj._data
        if d.dtype == jnp.bfloat16:
            return {_BF16_TAG: True,
                    "data": np.asarray(d.view(jnp.uint16)
                                       if hasattr(d, "view")
                                       else np.asarray(d.astype(jnp.float32)))}
        return np.asarray(d)
    if isinstance(obj, dict):
        return {k: _to_picklable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_picklable(v) for v in obj)
    return obj


def _from_picklable(obj):
    import jax.numpy as jnp

    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            arr = obj["data"]
            if arr.dtype == np.uint16:
                return Tensor._from_data(jnp.asarray(arr).view(jnp.bfloat16))
            return Tensor._from_data(jnp.asarray(arr, dtype=jnp.bfloat16))
        return {k: _from_picklable(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_picklable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_picklable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_picklable(obj)
