"""paddle.device — device selection + memory observability.

Reference: python/paddle/device/ (set_device:189) and the memory-stat
surface paddle.device.cuda.max_memory_allocated backed by
paddle/fluid/memory/stats.cc. Here the allocator is PJRT's; the stats
come from ``Device.memory_stats()`` (bytes_in_use / peak_bytes_in_use),
with a compiled-executable fallback (``memory_analysis``) for runtimes
that don't export allocator stats.
"""
from __future__ import annotations

from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CustomPlace, Place, TPUPlace, get_device, set_device,
    is_compiled_with_tpu,
)

__all__ = ["get_device", "set_device", "device_count",
           "memory_allocated", "max_memory_allocated",
           "memory_reserved", "max_memory_reserved",
           "reset_max_memory_allocated", "reset_peak_memory_stats",
           "memory_stats", "empty_cache", "get_memory_info"]


def _device(device=None):
    import jax

    devs = jax.local_devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    return device


def device_count() -> int:
    import jax

    return jax.local_device_count()


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats (may be {} when the runtime doesn't
    export them — e.g. remote-tunneled backends)."""
    d = _device(device)
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """Bytes currently held by live buffers on the device (reference
    paddle.device.cuda.memory_allocated / stats.cc Allocated)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """High-water mark of allocated bytes (reference
    max_memory_allocated / stats.cc peak value)."""
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved",
                     s.get("peak_bytes_in_use", 0)))


def reset_max_memory_allocated(device=None) -> None:
    """PJRT keeps its own peak counter; where the runtime can't reset
    it, this is a documented no-op (the reference resets an in-process
    counter, stats.cc)."""
    try:
        _device(device).clear_memory_stats()  # pragma: no cover
    except Exception:
        pass


reset_peak_memory_stats = reset_max_memory_allocated


def empty_cache() -> None:
    """Parity no-op: PJRT owns the buffer pool."""


def get_memory_info(device=None) -> dict:
    """Summary dict: allocated/peak/limit bytes where available."""
    s = memory_stats(device)
    return {
        "allocated": int(s.get("bytes_in_use", 0)),
        "peak_allocated": int(s.get("peak_bytes_in_use", 0)),
        "limit": int(s.get("bytes_limit", 0)),
    }


def compiled_memory_analysis(jitted_or_lowered) -> dict:
    """HBM footprint of ONE compiled executable (argument/output/temp/
    code bytes) — the fallback observability path when allocator stats
    are unavailable. Accepts a jax ``Compiled`` object or anything with
    ``memory_analysis()``."""
    ma = jitted_or_lowered.memory_analysis()
    out = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "generated_code_size_in_bytes",
              "alias_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out


# ---------------------------------------------------------------------------
# custom-device plugin seam (reference: paddle/phi/capi/ C ABI +
# backends/custom/custom_device.cc:47 C_DeviceInterface; python
# discovery device/__init__.py:46-50 CUSTOM_DEVICE_ROOT)
# ---------------------------------------------------------------------------

_registered_backends = {}


def register_backend(name, pjrt_plugin_path=None, factory=None,
                     priority=400, experimental=True):
    """Plug an external accelerator backend without modifying the
    framework — the reference's custom-device mechanism re-based on
    PJRT: hardware vendors ship a PJRT C-API plugin (`.so`), the
    framework registers it with the runtime and every op/collective
    works through the same XLA path (the role of the C kernel/CCL ABI
    in paddle/phi/capi/).

    ``pjrt_plugin_path``: path to a PJRT plugin shared library, loaded
    via jax's plugin discovery. ``factory``: alternatively a callable
    returning an xla_client.Client (in-process backends, tests).
    """
    import jax

    if name in _registered_backends:
        raise ValueError(f"backend {name!r} already registered")
    if (pjrt_plugin_path is None) == (factory is None):
        raise ValueError(
            "register_backend needs exactly one of pjrt_plugin_path "
            "(vendor .so) or factory (in-process client constructor)")
    if pjrt_plugin_path is not None:
        from jax._src.xla_bridge import register_plugin

        register_plugin(name, library_path=pjrt_plugin_path,
                        priority=priority)
    else:
        from jax._src.xla_bridge import register_backend_factory

        register_backend_factory(name, factory, priority=priority,
                                 experimental=experimental)
    _registered_backends[name] = pjrt_plugin_path or factory
    return name


def registered_backends():
    """Names registered through register_backend (the reference lists
    discovered custom devices in get_all_custom_device_type)."""
    return sorted(_registered_backends)


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return registered_backends()
