from paddle_tpu.optimizer.optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, ASGD, Lamb, LBFGS,
    Momentum, NAdam, Optimizer, RAdam, RMSProp, Rprop, SGD,
)
from paddle_tpu.optimizer import lr  # noqa: F401
