from paddle_tpu.optimizer.optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, NAdam,
    Optimizer, RAdam, RMSProp, SGD,
)
from paddle_tpu.optimizer import lr  # noqa: F401
