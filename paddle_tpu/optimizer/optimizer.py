"""Optimizers.

Reference: python/paddle/optimizer/optimizer.py + adam.py/adamw.py/....
Design: each optimizer defines a *pure functional rule*
``_rule(param, grad, slots, lr, step) -> (new_param, new_slots)`` over jax
arrays. Eager ``step()`` applies it per parameter; the jit path
(paddle_tpu/jit/train.py) applies the same rule inside the traced step so
eager and compiled training share one implementation — where the reference
needs separate eager ops and static-graph optimizer passes.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd import engine
from paddle_tpu.core.tensor import Tensor

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
           "Adam", "AdamW", "Adamax", "Lamb", "NAdam", "RAdam"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        from paddle_tpu.optimizer.lr import LRScheduler

        self._lr_scheduler: Optional[LRScheduler] = None
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            self._base_lr = None
        else:
            self._base_lr = float(learning_rate)
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list: Optional[List[Tensor]] = parameters
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._slots: Dict[int, dict] = {}
        self._step_count = 0
        # state_dict persists this callable's value as "step" when set:
        # a compiled train loop with an in-graph skip guard advances
        # _step_count per DISPATCH but rolls the device step back on a
        # skipped update — the APPLIED count is what a restore must see
        # (jit.TrainStep(skip_nonfinite=True) installs it; latest wins)
        self._applied_step_provider = None
        self._multi_precision = bool(multi_precision)
        # ASP n:m sparsity enforcement (incubate/asp): id(param) -> 0/1
        # mask, re-applied after every update; call sites set
        # _current_mask per param (trace-time static, like decay)
        self._param_masks: Dict[int, object] = {}
        self._current_mask = None

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return self._base_lr

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._base_lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr_scheduler if self._lr_scheduler is not None \
            else self._base_lr

    # -- functional core (override) ----------------------------------------
    def _init_slots(self, p) -> dict:
        return {}

    def _rule(self, p, g, slots, lr, step):
        raise NotImplementedError

    # -- dtype-stable / multi-precision wrappers (all call sites use these) --
    _multi_precision = False

    def _init_slots_mp(self, p) -> dict:
        """_init_slots plus, under multi_precision, an f32 master-weight
        slot for low-precision params (reference optimizer.py
        _multi_precision / master weights: python/paddle/optimizer/
        optimizer.py _create_master_weight)."""
        if self._multi_precision and jnp.issubdtype(p.dtype, jnp.floating) \
                and jnp.dtype(p.dtype).itemsize < 4:
            # moments/accumulators are created from the f32 master copy so
            # they accumulate in f32 (reference MPDType); bf16 moments
            # would freeze once (1-beta2)*g^2 drops below the bf16 quantum
            master = p.astype(jnp.float32)
            slots = self._init_slots(master)
            slots["master_weight"] = master
        else:
            slots = self._init_slots(p)
        fn = getattr(self, "_slot_shard_fn", None)
        if fn is not None:
            # dist.shard_optimizer(opt, ShardingStage1/2/3): place every
            # slot per the sharding rule (ZeRO-style states over dp)
            slots = {k: fn(k, p, v) for k, v in slots.items()}
        return slots

    def _rule_mp(self, p, g, slots, lr, step):
        """dtype-stable _rule: the updated param/slots keep their stored
        dtypes even when the rule computes in f32 (bf16 params must stay
        bf16 across steps or every jit step retraces), and the update is
        applied to the f32 master weight when one exists."""
        mw = slots.get("master_weight")
        if mw is not None:
            inner = {k: v for k, v in slots.items() if k != "master_weight"}
            new_mw, ns = self._rule(mw, g.astype(mw.dtype), inner, lr, step)
            ns = {k: (v.astype(inner[k].dtype)
                      if k in inner and hasattr(v, "astype") else v)
                  for k, v in ns.items()}
            if self._current_mask is not None:  # ASP n:m enforcement
                new_mw = new_mw * self._current_mask.astype(new_mw.dtype)
            ns["master_weight"] = new_mw.astype(jnp.float32)
            return new_mw.astype(p.dtype), ns
        new_p, ns = self._rule(p, g, slots, lr, step)
        ns = {k: (v.astype(slots[k].dtype)
                  if k in slots and hasattr(v, "astype") else v)
              for k, v in ns.items()}
        if self._current_mask is not None:  # ASP n:m enforcement
            new_p = new_p * self._current_mask.astype(new_p.dtype)
        return new_p.astype(p.dtype), ns

    # weight decay applied as decoupled or L2 depending on optimizer.
    # _current_decay_enabled is set per-parameter before each _rule call
    # (False when apply_decay_param_fun / exclude_from_weight_decay_fn
    # excludes the parameter); it is trace-time static so the jit TrainStep
    # sees the right branch per parameter.
    _current_decay_enabled = True

    def _decay_enabled(self, param) -> bool:
        return True

    def _apply_weight_decay_to_grad(self, p, g):
        wd = self._weight_decay
        if wd and self._current_decay_enabled:
            coeff = wd.coeff if hasattr(wd, "coeff") else float(wd)
            return g + coeff * p
        return g

    # -- eager step --------------------------------------------------------
    @engine.no_grad()
    def step(self):
        params = self._parameter_list or []
        grads = [(p, p.grad) for p in params
                 if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            clipped = self._grad_clip([(p, g) for p, g in grads])
            grads = clipped
        self._step_count += 1
        for p, g in grads:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self._init_slots_mp(p._data)
                self._slots[id(p)] = slots
            gdata = g._data if isinstance(g, Tensor) else g
            if gdata.dtype != p._data.dtype:
                gdata = gdata.astype(p._data.dtype)
            self._current_decay_enabled = self._decay_enabled(p)
            self._current_mask = self._param_masks.get(id(p))
            new_p, new_slots = self._rule_mp(p._data, gdata, slots,
                                             self.get_lr(), self._step_count)
            self._current_decay_enabled = True
            self._current_mask = None
            # params keep their user placement even when sharded slots
            # (dist.shard_optimizer ZeRO stages) would propagate their
            # sharding through the update math
            old_sh = getattr(p._data, "sharding", None)
            if old_sh is not None and \
                    getattr(new_p, "sharding", None) != old_sh:
                import jax

                new_p = jax.device_put(new_p, old_sh)
            p._data = new_p
            self._slots[id(p)] = new_slots

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from paddle_tpu import static as _static
        if isinstance(loss, _static.Variable):
            # Program mode: record the training objective; the Executor
            # compiles grads + this optimizer's pure rule into the step
            # (the append_backward + optimize-ops role). Parameters are
            # discovered from the program's trainable captures, like the
            # reference collects them from the global block.
            prog = loss._program
            if self._parameter_list is None:
                self._parameter_list = [
                    t for t in prog.captures if not t.stop_gradient]
            prog._train = (self, loss._sym)
            prog._bump()
            return
        loss.backward()
        self.step()
        self.clear_grad()

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        step = self._step_count
        if self._applied_step_provider is not None:
            applied = self._applied_step_provider()
            if applied is not None:
                step = int(applied)
        out = {"step": step}
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        names = self._param_names()
        for p, name in names.items():
            for k, v in self._slots.get(p, {}).items():
                out[f"{name}.{k}"] = Tensor._from_data(v) \
                    if not isinstance(v, Tensor) else v
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        if self._lr_scheduler is not None and "LR_Scheduler" in state:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        names = {v: k for k, v in self._param_names().items()}
        for key, val in state.items():
            if key in ("step", "LR_Scheduler"):
                continue
            pname, _, slot = key.rpartition(".")
            pid = names.get(pname)
            if pid is not None:
                data = val._data if isinstance(val, Tensor) else jnp.asarray(
                    val)
                self._slots.setdefault(pid, {})[slot] = data

    def _param_names(self):
        """Stable slot keys: a default auto name (tensor_N, global counter)
        differs between runs/processes, so substitute the position in the
        parameter list — deterministic given the same model structure.
        Explicit user names always win; a positional name that would
        collide with an explicit name gets an __auto suffix."""
        import re

        plist = self._parameter_list or []
        explicit = {p.name for p in plist
                    if not re.fullmatch(r"tensor_\d+", p.name or "")}
        out = {}
        for i, p in enumerate(plist):
            name = p.name
            if re.fullmatch(r"tensor_\d+", name or ""):
                name = f"param_{i}"
                if name in explicit:
                    name = f"param_{i}__auto"
            out[id(p)] = name
        return out


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            p2 = p - lr * (g + self._momentum * v)
        else:
            p2 = p - lr * v
        return p2, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        m = slots["moment"] + jnp.square(g)
        p2 = p - lr * g / (jnp.sqrt(m) + self._eps)
        return p2, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._eps = epsilon
        self._rho = rho

    def _init_slots(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p),
                "avg_sq_update": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        asg = self._rho * slots["avg_sq_grad"] + (1 - self._rho) * jnp.square(g)
        update = g * jnp.sqrt(slots["avg_sq_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * slots["avg_sq_update"] + \
            (1 - self._rho) * jnp.square(update)
        return p - lr * update, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p),
             "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        new = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            new["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new["momentum"] = mom
        return p - mom, new


class Adam(Optimizer):
    """Reference: python/paddle/optimizer/adam.py (L2-into-grad wd)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._multi_precision = bool(multi_precision)

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _decoupled(self):
        return False

    def _rule(self, p, g, slots, lr, step):
        if not self._decoupled():
            g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        if self._decoupled() and self._weight_decay and \
                self._current_decay_enabled:
            coeff = (self._weight_decay.coeff
                     if hasattr(self._weight_decay, "coeff")
                     else float(self._weight_decay))
            upd = upd + lr * coeff * p
        return p - upd, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._multi_precision = bool(multi_precision)

    def _decoupled(self):
        return True

    def _decay_enabled(self, param):
        if self._apply_decay_param_fun is not None:
            return bool(self._apply_decay_param_fun(param.name))
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        p2 = p - lr / (1 - b1 ** step) * m / (u + self._eps)
        return p2, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference:
    python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _decay_enabled(self, param):
        if self._exclude_fn is not None:
            # exclude_from_weight_decay_fn(param) -> True means EXCLUDE
            return not bool(self._exclude_fn(param))
        return True

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = (float(self._weight_decay)
              if self._weight_decay and self._current_decay_enabled else 0.0)
        r = r + wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * ratio * r, {"moment1": m, "moment2": v}


class NAdam(Adam):
    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** (step + 1))
        vhat = v / (1 - b2 ** step)
        m_bar = b1 * mhat + (1 - b1) * g / (1 - b1 ** step)
        return p - lr * m_bar / (jnp.sqrt(vhat) + self._eps), \
            {"moment1": m, "moment2": v}


class RAdam(Adam):
    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        rho_inf = 2.0 / (1 - b2) - 1.0
        # step may be a traced value under TrainStep; branch via jnp.where
        rho_t = rho_inf - 2.0 * step * (b2 ** step) / (1 - b2 ** step)
        vhat = jnp.sqrt(v / (1 - b2 ** step))
        rt = jnp.sqrt(jnp.maximum(
            ((rho_t - 4.0) * (rho_t - 2.0) * rho_inf) /
            ((rho_inf - 4.0) * (rho_inf - 2.0) *
             jnp.maximum(rho_t, self._eps)), 0.0))
        rectified = p - lr * rt * mhat / (vhat + self._eps)
        unrectified = p - lr * mhat
        p2 = jnp.where(rho_t > 4.0, rectified, unrectified)
        return p2, {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    """Averaged SGD over a window of the last ``batch_num`` gradients
    (reference: python/paddle/optimizer/asgd.py:29 —
    x ← x − lr·(d/min(t+1, n) + λx) with d the running sum of the last n
    grads held in a circular buffer). Memory: n copies of each param's
    grad, as in the reference's ``ys`` accumulator."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None, **kw):
        if batch_num is None or batch_num <= 0:
            raise ValueError("batch_num should be greater than 0")
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip)
        self._multi_precision = bool(multi_precision)
        self._n = int(batch_num)

    def _init_slots(self, p):
        return {"d": jnp.zeros_like(p),
                "ys": jnp.zeros((self._n,) + p.shape, p.dtype)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        n = self._n
        idx = (jnp.asarray(step, jnp.int32) - 1) % n
        old = jax.lax.dynamic_index_in_dim(slots["ys"], idx, 0,
                                           keepdims=False)
        d = slots["d"] - old + g
        ys = jax.lax.dynamic_update_index_in_dim(slots["ys"], g, idx, 0)
        m = jnp.minimum(jnp.asarray(step, p.dtype), float(n))
        p2 = p - lr * d / jnp.maximum(m, 1.0)
        return p2, {"d": d, "ys": ys}


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py:28):
    per-element step sizes grown by ``etas[1]`` on consecutive same-sign
    grads, shrunk by ``etas[0]`` on sign flips (the flip step is skipped,
    Rprop⁻), clipped to ``learning_rate_range``. Single-batch regimes
    only, as the reference documents."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None, **kw):
        if not (0.0 < learning_rate_range[0] <= learning_rate
                <= learning_rate_range[1]):
            raise ValueError(
                "'0.0 < learning_rate_range[0] <= learning_rate <= "
                "learning_rate_range[1]' must be true")
        if not (0.0 < etas[0] < 1.0 <= etas[1]):
            raise ValueError("'0.0 < etas[0] < 1.0 <= etas[1]' must be true")
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._multi_precision = bool(multi_precision)
        self._lr0 = float(learning_rate)
        self._range = (float(learning_rate_range[0]),
                       float(learning_rate_range[1]))
        self._etas = (float(etas[0]), float(etas[1]))

    def _init_slots(self, p):
        return {"prev": jnp.zeros_like(p),
                "lrs": jnp.full(p.shape, self._lr0, p.dtype)}

    def _rule(self, p, g, slots, lr, step):
        lo, hi = self._range
        eminus, eplus = self._etas
        sign = jnp.sign(g * slots["prev"])
        lrs = jnp.where(sign > 0,
                        jnp.minimum(slots["lrs"] * eplus, hi),
                        jnp.where(sign < 0,
                                  jnp.maximum(slots["lrs"] * eminus, lo),
                                  slots["lrs"]))
        g_eff = jnp.where(sign < 0, 0.0, g)
        p2 = p - jnp.sign(g_eff) * lrs
        return p2, {"prev": g_eff, "lrs": lrs}


class LBFGS(Optimizer):
    """Limited-memory BFGS with optional strong-Wolfe line search
    (reference: python/paddle/optimizer/lbfgs.py:315 — the closure-based
    ``step(closure)`` API; two-loop recursion over ``history_size``
    curvature pairs; ``line_search_fn='strong_wolfe'`` runs
    cubic-interpolation zoom as in ``_strong_wolfe``)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip)
        self._max_iter = int(max_iter)
        self._max_eval = int(max_eval) if max_eval is not None \
            else self._max_iter * 5 // 4
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._history = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                "line_search_fn must be None or 'strong_wolfe'")
        self._line_search = line_search_fn
        self._state = {"old_dirs": [], "old_stps": [], "ro": [],
                       "prev_flat_grad": None, "d": None, "t": None,
                       "H_diag": 1.0, "n_iter": 0, "func_evals": 0}

    # ---- flatten helpers -------------------------------------------------
    def _params(self):
        return [p for p in (self._parameter_list or [])
                if not p.stop_gradient]

    def _gather_flat_grad(self):
        gs = []
        for p in self._params():
            g = p.grad._data if p.grad is not None else \
                jnp.zeros_like(p._data)
            # weight decay folds into the objective's gradient so the
            # line search sees the regularized objective too
            self._current_decay_enabled = self._decay_enabled(p)
            g = self._apply_weight_decay_to_grad(p._data, g)
            self._current_decay_enabled = True
            gs.append(g)
        clip_fn = getattr(self._grad_clip, "clip_fn", None)
        if clip_fn is not None:
            gs = clip_fn(gs)
        elif self._grad_clip is not None:
            raise NotImplementedError(
                "LBFGS supports grad clips with a pure clip_fn "
                "(ClipGradByGlobalNorm)")
        return jnp.concatenate(
            [jnp.ravel(g.astype(jnp.float32)) for g in gs])

    def _add_to_params(self, step_size, update_flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p._data.shape)) if p._data.ndim else 1
            seg = update_flat[off:off + n].reshape(p._data.shape)
            p._data = p._data + (step_size * seg).astype(p._data.dtype)
            off += n

    def _clone_params(self):
        return [p._data for p in self._params()]

    def _restore_params(self, saved):
        for p, d in zip(self._params(), saved):
            p._data = d

    def _call_closure(self, closure):
        # grad recording must be ON regardless of the caller's context —
        # the closure's backward() is what feeds the line search
        with engine.enable_grad():
            return closure()

    def _eval(self, closure, x0, t, d):
        self._restore_params(x0)
        self._add_to_params(t, d)
        loss = float(self._call_closure(closure))
        flat_grad = self._gather_flat_grad()
        self._state["func_evals"] += 1
        return loss, flat_grad

    def step(self, closure=None):
        """Reference contract: ``closure`` re-evaluates the model and
        returns the loss (it must call ``backward()``)."""
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        state = self._state
        self._step_count += 1
        lr = float(self.get_lr())

        orig_loss = self._call_closure(closure)
        loss = float(orig_loss)
        flat_grad = self._gather_flat_grad()
        if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
            return orig_loss

        n_iter = 0
        while n_iter < self._max_iter:
            n_iter += 1
            state["n_iter"] += 1
            if state["n_iter"] == 1:
                d = -flat_grad
                state["old_dirs"], state["old_stps"], state["ro"] = \
                    [], [], []
                H_diag = 1.0
            else:
                y = flat_grad - state["prev_flat_grad"]
                s = state["d"] * state["t"]
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(state["old_dirs"]) >= self._history:
                        state["old_dirs"].pop(0)
                        state["old_stps"].pop(0)
                        state["ro"].pop(0)
                    state["old_dirs"].append(y)
                    state["old_stps"].append(s)
                    state["ro"].append(1.0 / ys)
                    H_diag = ys / float(jnp.dot(y, y))
                else:
                    H_diag = state["H_diag"]
                # two-loop recursion
                q = -flat_grad
                al = []
                for y_i, s_i, ro_i in zip(reversed(state["old_dirs"]),
                                          reversed(state["old_stps"]),
                                          reversed(state["ro"])):
                    a = ro_i * float(jnp.dot(s_i, q))
                    al.append(a)
                    q = q - a * y_i
                d = q * H_diag
                for (y_i, s_i, ro_i), a in zip(
                        zip(state["old_dirs"], state["old_stps"],
                            state["ro"]), reversed(al)):
                    b = ro_i * float(jnp.dot(y_i, d))
                    d = d + s_i * (a - b)
            state["H_diag"] = H_diag
            state["prev_flat_grad"] = flat_grad

            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self._tol_change:
                break
            t = min(1.0, 1.0 / float(jnp.abs(flat_grad).sum())) * lr \
                if state["n_iter"] == 1 else lr

            if self._line_search == "strong_wolfe":
                x0 = self._clone_params()
                loss, flat_grad, t = self._strong_wolfe(
                    closure, x0, t, d, loss, flat_grad, gtd)
                self._restore_params(x0)
                self._add_to_params(t, d)
            else:
                self._add_to_params(t, d)
                if n_iter < self._max_iter:
                    loss = float(self._call_closure(closure))
                    flat_grad = self._gather_flat_grad()
            state["d"], state["t"] = d, t

            if state["func_evals"] >= self._max_eval:
                break
            if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
                break
            if float(jnp.abs(d * t).max()) <= self._tol_change:
                break
        return orig_loss

    def _strong_wolfe(self, closure, x0, t, d, f0, g0, gtd0,
                      c1=1e-4, c2=0.9, max_ls=25):
        """Strong-Wolfe line search with cubic-interpolation zoom
        (reference lbfgs.py _strong_wolfe)."""

        def cubic_min(x1, f1, g1, x2, f2, g2):
            d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
            sq = d1 * d1 - g1 * g2
            if sq < 0:
                return (x1 + x2) / 2.0
            d2 = np.sqrt(sq)
            if x1 <= x2:
                xm = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
            else:
                xm = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
            lo, hi = min(x1, x2), max(x1, x2)
            return float(np.clip(xm, lo + 0.1 * (hi - lo),
                                 hi - 0.1 * (hi - lo)))

        f_prev, g_prev, t_prev = f0, g0, 0.0
        gtd_prev = gtd0
        ls_iter = 0
        while ls_iter < max_ls:
            f_new, g_new = self._eval(closure, x0, t, d)
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or \
                    (ls_iter > 0 and f_new >= f_prev):
                return self._zoom(closure, x0, d, f0, gtd0, t_prev,
                                  f_prev, gtd_prev, t, f_new, gtd_new,
                                  c1, c2, max_ls - ls_iter, cubic_min)
            if abs(gtd_new) <= -c2 * gtd0:
                return f_new, g_new, t
            if gtd_new >= 0:
                return self._zoom(closure, x0, d, f0, gtd0, t, f_new,
                                  gtd_new, t_prev, f_prev, gtd_prev,
                                  c1, c2, max_ls - ls_iter, cubic_min)
            t_prev, f_prev, gtd_prev = t, f_new, gtd_new
            t = min(t * 2.0, 10.0)
            ls_iter += 1
        return f_new, g_new, t

    def _zoom(self, closure, x0, d, f0, gtd0, t_lo, f_lo, gtd_lo, t_hi,
              f_hi, gtd_hi, c1, c2, max_ls, cubic_min):
        f_new, g_new, t = f_lo, None, t_lo
        for _ in range(max(int(max_ls), 1)):
            t = cubic_min(t_lo, f_lo, gtd_lo, t_hi, f_hi, gtd_hi)
            f_new, g_new = self._eval(closure, x0, t, d)
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                t_hi, f_hi, gtd_hi = t, f_new, gtd_new
            else:
                if abs(gtd_new) <= -c2 * gtd0:
                    return f_new, g_new, t
                if gtd_new * (t_hi - t_lo) >= 0:
                    t_hi, f_hi, gtd_hi = t_lo, f_lo, gtd_lo
                t_lo, f_lo, gtd_lo = t, f_new, gtd_new
            if abs(t_hi - t_lo) < 1e-9:
                break
        if g_new is None:
            f_new, g_new = self._eval(closure, x0, t, d)
        return f_new, g_new, t
