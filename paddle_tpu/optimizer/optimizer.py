"""Optimizers.

Reference: python/paddle/optimizer/optimizer.py + adam.py/adamw.py/....
Design: each optimizer defines a *pure functional rule*
``_rule(param, grad, slots, lr, step) -> (new_param, new_slots)`` over jax
arrays. Eager ``step()`` applies it per parameter; the jit path
(paddle_tpu/jit/train.py) applies the same rule inside the traced step so
eager and compiled training share one implementation — where the reference
needs separate eager ops and static-graph optimizer passes.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from paddle_tpu.autograd import engine
from paddle_tpu.core.tensor import Tensor

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
           "Adam", "AdamW", "Adamax", "Lamb", "NAdam", "RAdam"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        from paddle_tpu.optimizer.lr import LRScheduler

        self._lr_scheduler: Optional[LRScheduler] = None
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            self._base_lr = None
        else:
            self._base_lr = float(learning_rate)
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list: Optional[List[Tensor]] = parameters
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._slots: Dict[int, dict] = {}
        self._step_count = 0
        self._multi_precision = bool(multi_precision)
        # ASP n:m sparsity enforcement (incubate/asp): id(param) -> 0/1
        # mask, re-applied after every update; call sites set
        # _current_mask per param (trace-time static, like decay)
        self._param_masks: Dict[int, object] = {}
        self._current_mask = None

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return self._base_lr

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._base_lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr_scheduler if self._lr_scheduler is not None \
            else self._base_lr

    # -- functional core (override) ----------------------------------------
    def _init_slots(self, p) -> dict:
        return {}

    def _rule(self, p, g, slots, lr, step):
        raise NotImplementedError

    # -- dtype-stable / multi-precision wrappers (all call sites use these) --
    _multi_precision = False

    def _init_slots_mp(self, p) -> dict:
        """_init_slots plus, under multi_precision, an f32 master-weight
        slot for low-precision params (reference optimizer.py
        _multi_precision / master weights: python/paddle/optimizer/
        optimizer.py _create_master_weight)."""
        if self._multi_precision and jnp.issubdtype(p.dtype, jnp.floating) \
                and jnp.dtype(p.dtype).itemsize < 4:
            # moments/accumulators are created from the f32 master copy so
            # they accumulate in f32 (reference MPDType); bf16 moments
            # would freeze once (1-beta2)*g^2 drops below the bf16 quantum
            master = p.astype(jnp.float32)
            slots = self._init_slots(master)
            slots["master_weight"] = master
        else:
            slots = self._init_slots(p)
        fn = getattr(self, "_slot_shard_fn", None)
        if fn is not None:
            # dist.shard_optimizer(opt, ShardingStage1/2/3): place every
            # slot per the sharding rule (ZeRO-style states over dp)
            slots = {k: fn(k, p, v) for k, v in slots.items()}
        return slots

    def _rule_mp(self, p, g, slots, lr, step):
        """dtype-stable _rule: the updated param/slots keep their stored
        dtypes even when the rule computes in f32 (bf16 params must stay
        bf16 across steps or every jit step retraces), and the update is
        applied to the f32 master weight when one exists."""
        mw = slots.get("master_weight")
        if mw is not None:
            inner = {k: v for k, v in slots.items() if k != "master_weight"}
            new_mw, ns = self._rule(mw, g.astype(mw.dtype), inner, lr, step)
            ns = {k: (v.astype(inner[k].dtype)
                      if k in inner and hasattr(v, "astype") else v)
                  for k, v in ns.items()}
            if self._current_mask is not None:  # ASP n:m enforcement
                new_mw = new_mw * self._current_mask.astype(new_mw.dtype)
            ns["master_weight"] = new_mw.astype(jnp.float32)
            return new_mw.astype(p.dtype), ns
        new_p, ns = self._rule(p, g, slots, lr, step)
        ns = {k: (v.astype(slots[k].dtype)
                  if k in slots and hasattr(v, "astype") else v)
              for k, v in ns.items()}
        if self._current_mask is not None:  # ASP n:m enforcement
            new_p = new_p * self._current_mask.astype(new_p.dtype)
        return new_p.astype(p.dtype), ns

    # weight decay applied as decoupled or L2 depending on optimizer.
    # _current_decay_enabled is set per-parameter before each _rule call
    # (False when apply_decay_param_fun / exclude_from_weight_decay_fn
    # excludes the parameter); it is trace-time static so the jit TrainStep
    # sees the right branch per parameter.
    _current_decay_enabled = True

    def _decay_enabled(self, param) -> bool:
        return True

    def _apply_weight_decay_to_grad(self, p, g):
        wd = self._weight_decay
        if wd and self._current_decay_enabled:
            coeff = wd.coeff if hasattr(wd, "coeff") else float(wd)
            return g + coeff * p
        return g

    # -- eager step --------------------------------------------------------
    @engine.no_grad()
    def step(self):
        params = self._parameter_list or []
        grads = [(p, p.grad) for p in params
                 if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            clipped = self._grad_clip([(p, g) for p, g in grads])
            grads = clipped
        self._step_count += 1
        for p, g in grads:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self._init_slots_mp(p._data)
                self._slots[id(p)] = slots
            gdata = g._data if isinstance(g, Tensor) else g
            if gdata.dtype != p._data.dtype:
                gdata = gdata.astype(p._data.dtype)
            self._current_decay_enabled = self._decay_enabled(p)
            self._current_mask = self._param_masks.get(id(p))
            new_p, new_slots = self._rule_mp(p._data, gdata, slots,
                                             self.get_lr(), self._step_count)
            self._current_decay_enabled = True
            self._current_mask = None
            # params keep their user placement even when sharded slots
            # (dist.shard_optimizer ZeRO stages) would propagate their
            # sharding through the update math
            old_sh = getattr(p._data, "sharding", None)
            if old_sh is not None and \
                    getattr(new_p, "sharding", None) != old_sh:
                import jax

                new_p = jax.device_put(new_p, old_sh)
            p._data = new_p
            self._slots[id(p)] = new_slots

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from paddle_tpu import static as _static
        if isinstance(loss, _static.Variable):
            # Program mode: record the training objective; the Executor
            # compiles grads + this optimizer's pure rule into the step
            # (the append_backward + optimize-ops role). Parameters are
            # discovered from the program's trainable captures, like the
            # reference collects them from the global block.
            prog = loss._program
            if self._parameter_list is None:
                self._parameter_list = [
                    t for t in prog.captures if not t.stop_gradient]
            prog._train = (self, loss._sym)
            prog._bump()
            return
        loss.backward()
        self.step()
        self.clear_grad()

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        names = self._param_names()
        for p, name in names.items():
            for k, v in self._slots.get(p, {}).items():
                out[f"{name}.{k}"] = Tensor._from_data(v) \
                    if not isinstance(v, Tensor) else v
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        if self._lr_scheduler is not None and "LR_Scheduler" in state:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        names = {v: k for k, v in self._param_names().items()}
        for key, val in state.items():
            if key in ("step", "LR_Scheduler"):
                continue
            pname, _, slot = key.rpartition(".")
            pid = names.get(pname)
            if pid is not None:
                data = val._data if isinstance(val, Tensor) else jnp.asarray(
                    val)
                self._slots.setdefault(pid, {})[slot] = data

    def _param_names(self):
        """Stable slot keys: a default auto name (tensor_N, global counter)
        differs between runs/processes, so substitute the position in the
        parameter list — deterministic given the same model structure.
        Explicit user names always win; a positional name that would
        collide with an explicit name gets an __auto suffix."""
        import re

        plist = self._parameter_list or []
        explicit = {p.name for p in plist
                    if not re.fullmatch(r"tensor_\d+", p.name or "")}
        out = {}
        for i, p in enumerate(plist):
            name = p.name
            if re.fullmatch(r"tensor_\d+", name or ""):
                name = f"param_{i}"
                if name in explicit:
                    name = f"param_{i}__auto"
            out[id(p)] = name
        return out


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            p2 = p - lr * (g + self._momentum * v)
        else:
            p2 = p - lr * v
        return p2, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        m = slots["moment"] + jnp.square(g)
        p2 = p - lr * g / (jnp.sqrt(m) + self._eps)
        return p2, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._eps = epsilon
        self._rho = rho

    def _init_slots(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p),
                "avg_sq_update": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        asg = self._rho * slots["avg_sq_grad"] + (1 - self._rho) * jnp.square(g)
        update = g * jnp.sqrt(slots["avg_sq_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * slots["avg_sq_update"] + \
            (1 - self._rho) * jnp.square(update)
        return p - lr * update, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p),
             "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        new = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            new["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new["momentum"] = mom
        return p - mom, new


class Adam(Optimizer):
    """Reference: python/paddle/optimizer/adam.py (L2-into-grad wd)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._multi_precision = bool(multi_precision)

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _decoupled(self):
        return False

    def _rule(self, p, g, slots, lr, step):
        if not self._decoupled():
            g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        if self._decoupled() and self._weight_decay and \
                self._current_decay_enabled:
            coeff = (self._weight_decay.coeff
                     if hasattr(self._weight_decay, "coeff")
                     else float(self._weight_decay))
            upd = upd + lr * coeff * p
        return p - upd, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._multi_precision = bool(multi_precision)

    def _decoupled(self):
        return True

    def _decay_enabled(self, param):
        if self._apply_decay_param_fun is not None:
            return bool(self._apply_decay_param_fun(param.name))
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        p2 = p - lr / (1 - b1 ** step) * m / (u + self._eps)
        return p2, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference:
    python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._multi_precision = bool(kw.get("multi_precision", False))
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _decay_enabled(self, param):
        if self._exclude_fn is not None:
            # exclude_from_weight_decay_fn(param) -> True means EXCLUDE
            return not bool(self._exclude_fn(param))
        return True

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = (float(self._weight_decay)
              if self._weight_decay and self._current_decay_enabled else 0.0)
        r = r + wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * ratio * r, {"moment1": m, "moment2": v}


class NAdam(Adam):
    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** (step + 1))
        vhat = v / (1 - b2 ** step)
        m_bar = b1 * mhat + (1 - b1) * g / (1 - b1 ** step)
        return p - lr * m_bar / (jnp.sqrt(vhat) + self._eps), \
            {"moment1": m, "moment2": v}


class RAdam(Adam):
    def _rule(self, p, g, slots, lr, step):
        g = self._apply_weight_decay_to_grad(p, g)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** step)
        rho_inf = 2.0 / (1 - b2) - 1.0
        # step may be a traced value under TrainStep; branch via jnp.where
        rho_t = rho_inf - 2.0 * step * (b2 ** step) / (1 - b2 ** step)
        vhat = jnp.sqrt(v / (1 - b2 ** step))
        rt = jnp.sqrt(jnp.maximum(
            ((rho_t - 4.0) * (rho_t - 2.0) * rho_inf) /
            ((rho_inf - 4.0) * (rho_inf - 2.0) *
             jnp.maximum(rho_t, self._eps)), 0.0))
        rectified = p - lr * rt * mhat / (vhat + self._eps)
        unrectified = p - lr * mhat
        p2 = jnp.where(rho_t > 4.0, rectified, unrectified)
        return p2, {"moment1": m, "moment2": v}
