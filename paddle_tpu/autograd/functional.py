"""Functional / higher-order autodiff.

Reference: python/paddle/autograd/autograd.py (Jacobian/Hessian) and
python/paddle/incubate/autograd/functional.py:22,80 (vjp/jvp). Here these are
direct bridges to JAX's transforms — forward-mode (jvp), reverse (vjp/grad),
and composed jacfwd/jacrev — which is the whole point of building on a
functional substrate: the reference needed a separate "prim" system
(paddle/fluid/primitive/) to get composable transforms; XLA-first we inherit
them.
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Union

import jax

from paddle_tpu.autograd import engine
from paddle_tpu.core.tensor import Tensor

__all__ = ["grad", "jacobian", "hessian", "vjp", "jvp", "Jacobian", "Hessian"]


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False, no_grad_vars=None):
    """paddle.grad: grads of ``outputs`` wrt ``inputs`` without touching
    ``.grad`` accumulators."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)

    # stash .grad only — _acc_node stays so registered leaf hooks fire
    # during paddle.grad, matching the reference's hook contract
    stash = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None
    try:
        engine.backward(outputs, grad_outputs,
                        retain_graph=retain_graph or create_graph,
                        create_graph=create_graph, grad_targets=inputs)
        results = []
        for i, t in enumerate(inputs):
            if t.grad is None:
                if not allow_unused:
                    raise ValueError(
                        f"The {i}-th input does not appear in the backward "
                        "graph of the given outputs. Pass allow_unused=True "
                        "to get None for unreachable inputs (reference "
                        "contract: python/paddle/base/dygraph/base.py grad)")
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, g in zip(inputs, stash):
            t.grad = g


def _functionalize(func: Callable):
    """Wrap a Tensor->Tensor function as a pure jax-array function."""

    def fn(*datas):
        ins = [Tensor._from_data(d, stop_gradient=False) for d in datas]
        out = func(*ins) if len(ins) > 1 else func(ins[0])
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data

    return fn


def _unpack(xs):
    single = isinstance(xs, Tensor)
    datas = [xs._data] if single else [x._data for x in xs]
    return single, datas


def vjp(func, xs, v=None):
    """(outputs, vjp_result) — reference: incubate/autograd/functional.py:22."""
    single, datas = _unpack(xs)
    out, vjp_fn = jax.vjp(_functionalize(func), *datas)
    if v is None:
        import jax.numpy as jnp
        v_data = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_data = v._data if isinstance(v, Tensor) else tuple(
            t._data for t in v)
    grads = vjp_fn(v_data)
    out_t = _wrap(out)
    grads_t = [Tensor._from_data(g) for g in grads]
    return out_t, grads_t[0] if single else grads_t


def jvp(func, xs, v=None):
    single, datas = _unpack(xs)
    if v is None:
        import jax.numpy as jnp
        tangents = tuple(jnp.ones_like(d) for d in datas)
    else:
        vs = [v] if isinstance(v, Tensor) else list(v)
        tangents = tuple(t._data for t in vs)
    out, tang = jax.jvp(_functionalize(func), tuple(datas), tangents)
    return _wrap(out), _wrap(tang)


def _wrap(out):
    if isinstance(out, tuple):
        return tuple(Tensor._from_data(o) for o in out)
    return Tensor._from_data(out)


def jacobian(func, xs, batch_axis=None):
    """Dense Jacobian (lazy in the reference, eager here)."""
    single, datas = _unpack(xs)
    jac = jax.jacrev(_functionalize(func), argnums=tuple(range(len(datas))))(
        *datas)
    if single:
        jac = jac[0] if isinstance(jac, tuple) else jac
        return _wrap(jac)
    return [_wrap(j) for j in jac]


def hessian(func, xs, batch_axis=None):
    single, datas = _unpack(xs)
    hes = jax.hessian(_functionalize(func), argnums=tuple(range(len(datas))))(
        *datas)
    if single:
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return _wrap(h)
    return [[_wrap(c) for c in row] for row in hes]


# class-style API parity (paddle.autograd.Jacobian / Hessian)
class Jacobian:
    def __init__(self, func, xs, is_batched=False):
        self._value = jacobian(func, xs)

    def __getitem__(self, idx):
        return self._value[idx]

    @property
    def value(self):
        return self._value


class Hessian(Jacobian):
    def __init__(self, func, xs, is_batched=False):
        self._value = hessian(func, xs)
