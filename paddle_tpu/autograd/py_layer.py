"""PyLayer: user-defined forward/backward.

Reference: python/paddle/autograd/py_layer.py + paddle/fluid/eager/pylayer/.
The user's backward is spliced into the tape as a custom GradNode, exactly
where a vjp closure would sit.
"""
from __future__ import annotations

from typing import Any, List

from paddle_tpu.autograd import engine
from paddle_tpu.core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self._attrs: dict = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    # arbitrary attribute stashing, paddle-compatible
    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)

    def mark_not_inplace(self, *a):  # API parity no-ops
        pass

    def mark_non_differentiable(self, *a):
        pass

    def set_materialize_grads(self, v):
        pass


class PyLayer:
    """Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    staticmethods; call via ``MyLayer.apply(*args)``."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        # run forward without tape recording; user ops inside are opaque
        with engine.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if multi else [outputs]
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]

        # differentiable inputs, in forward-arg order
        diff_inputs = [
            a for a in list(args) + list(kwargs.values())
            if isinstance(a, Tensor) and not a.stop_gradient
        ]

        if engine.is_grad_enabled() and diff_inputs:
            import jax

            out_avals = [
                jax.ShapeDtypeStruct(o._data.shape, o._data.dtype)
                for o in out_tensors
            ]

            def vjp_fn(cotangents):
                cots = (
                    list(cotangents)
                    if isinstance(cotangents, (tuple, list))
                    else [cotangents]
                )
                grad_tensors = [Tensor._from_data(c) for c in cots]
                with engine.no_grad():
                    in_grads = cls.backward(ctx, *grad_tensors)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                return tuple(
                    g._data if isinstance(g, Tensor) else g for g in in_grads
                )

            node = engine.GradNode(cls.__name__, vjp_fn, diff_inputs, out_avals)
            for i, o in enumerate(out_tensors):
                o.stop_gradient = False
                o._grad_node = node
                o._output_index = i
        return outputs

    # paddle naming parity
    once_differentiable = staticmethod(lambda f: f)


def once_differentiable(f):
    return f
