"""Define-by-run autograd engine.

TPU-native analog of the reference's eager autograd
(``egr::GradNodeBase`` paddle/fluid/eager/grad_node_info.h:197,
``egr::RunBackward`` paddle/fluid/eager/backward.cc:105,
``GradTensorHolder`` grad_tensor_holder.cc, in-degree pass backward.cc:23).

Design difference from the reference: instead of hand-written/generated
per-op grad kernels, every op's backward is obtained from ``jax.vjp`` over its
XLA emitter — one autodiff rulebook (JAX's) for the whole op surface. A
GradNode stores the vjp closure (which holds XLA residual buffers, playing the
role of the reference's TensorWrapper saved tensors) and edges to the input
tensors' nodes.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "AccumulationNode", "backward", "no_grad", "enable_grad",
    "is_grad_enabled", "set_grad_enabled", "register_node", "Hook",
    "register_post_backward_callback",
]

_state = threading.local()

# Callbacks fired once after each backward() finishes draining its queue —
# the seam where the reference's EagerReducer finalizes gradient buckets
# (paddle/fluid/distributed/collective/reducer.cc FinalizeBackward).
_post_backward_callbacks: List[Callable] = []


def register_post_backward_callback(fn: Callable):
    """Register fn() to run at the end of every backward(). Returns a
    remover handle."""
    _post_backward_callbacks.append(fn)

    def remove():
        try:
            _post_backward_callbacks.remove(fn)
        except ValueError:
            pass

    return remove


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class _NoGrad(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad():
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    return _NoGrad(False)


def enable_grad():
    return _NoGrad(True)


class GradNode:
    """One recorded op in the backward graph.

    ``vjp_fn(cotangents_tuple) -> input cotangents tuple`` where cotangents
    match the op's (possibly multi-) output structure.
    """

    __slots__ = (
        "name", "vjp_fn", "inputs", "out_avals", "pending", "n_expected",
        "n_seen", "hooks", "__weakref__",
    )

    def __init__(
        self,
        name: str,
        vjp_fn: Callable,
        inputs: Sequence,  # list[Optional[Tensor]] — None for non-diff inputs
        out_avals: Sequence,  # list[jax.ShapeDtypeStruct] for each output
    ):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        self.out_avals = list(out_avals)
        # filled during backward:
        self.pending: Optional[list] = None  # per-output accumulated cotangent
        self.n_expected = 0
        self.n_seen = 0
        self.hooks: List[Callable] = []

    def register_hook(self, fn: Callable):
        """fn(grads_tuple) -> grads_tuple, fired before applying vjp."""
        self.hooks.append(fn)


class AccumulationNode:
    """Terminal node for a leaf tensor; accumulates into ``tensor.grad``.

    Analog of ``egr::GradNodeAccumulation``
    (reference: paddle/fluid/eager/accumulation/accumulation_node.h).
    """

    __slots__ = ("tensor_ref", "hooks", "__weakref__")

    def __init__(self, tensor):
        import weakref

        self.tensor_ref = weakref.ref(tensor)
        self.hooks: List[Callable] = []


def register_node(outputs, name, vjp_fn, diff_inputs):
    """Attach a fresh GradNode to op outputs.

    ``outputs``: list of Tensors produced by the op.
    ``diff_inputs``: list of Optional[Tensor] aligned with vjp inputs.
    """
    out_avals = [
        jax.ShapeDtypeStruct(o._data.shape, o._data.dtype) for o in outputs
    ]
    node = GradNode(name, vjp_fn, diff_inputs, out_avals)
    for i, o in enumerate(outputs):
        if not o.stop_gradient:
            o._grad_node = node
            o._output_index = i
    return node


def _producer(tensor):
    """The node that produces ``tensor``'s gradient demand, if any."""
    if tensor is None or tensor.stop_gradient:
        return None
    node = tensor._grad_node
    if node is None:
        # leaf requiring grad -> accumulation
        acc = tensor._acc_node
        if acc is None:
            acc = AccumulationNode(tensor)
            tensor._acc_node = acc
        return acc
    return node


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse accumulation from ``tensors``.

    Mirrors ``egr::RunBackward`` (paddle/fluid/eager/backward.cc:105): build
    the in-degree map over reachable nodes, seed with the output cotangents,
    then ready-queue topological execution.
    """
    from paddle_tpu.core.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # ---- seed roots -----------------------------------------------------
    roots = {}
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        node = t._grad_node
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape {tuple(t._data.shape)})"
                )
            gdata = jnp.ones_like(t._data)
        else:
            gdata = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if node is None:
            _accumulate_leaf(t, gdata)
            continue
        idx = t._output_index
        slots = roots.setdefault(node, {})
        slots[idx] = slots[idx] + gdata if idx in slots else gdata

    if not roots:
        return

    # ---- in-degree over reachable GradNodes ------------------------------
    indegree: dict = {}
    stack = list(roots.keys())
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen or not isinstance(node, GradNode):
            continue
        seen.add(id(node))
        indegree.setdefault(id(node), 0)
        for inp in node.inputs:
            prod = _producer(inp)
            if isinstance(prod, GradNode):
                indegree[id(prod)] = indegree.get(id(prod), 0) + 1
                stack.append(prod)

    # ---- ready-queue execution ------------------------------------------
    pending: dict = {}  # id(node) -> {out_idx: cotangent}
    node_by_id = {}
    queue = []
    for node, slots in roots.items():
        pending[id(node)] = slots
        node_by_id[id(node)] = node
        if indegree.get(id(node), 0) == 0:
            queue.append(node)

    executed = set()
    while queue:
        node = queue.pop()
        if id(node) in executed:
            continue
        executed.add(id(node))
        slots = pending.pop(id(node), {})

        # build full cotangent tuple (zeros for outputs nobody needs;
        # int/bool outputs take float0 tangents per JAX's convention)
        cotangents = tuple(
            slots.get(i, _zero_cotangent(av)) for i, av in enumerate(node.out_avals)
        )
        for hook in node.hooks:
            cotangents = hook(cotangents)

        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward through the graph a second time, "
                "but the saved residuals have already been freed. Pass "
                "retain_graph=True to the first backward() if you need to "
                "backward through this graph again.")
        in_grads = node.vjp_fn(
            cotangents if len(cotangents) > 1 else cotangents[0]
        )
        if not isinstance(in_grads, (tuple, list)):
            in_grads = (in_grads,)

        if not retain_graph:
            node.vjp_fn = None  # free residuals

        for inp, g in zip(node.inputs, in_grads):
            if inp is None or g is None:
                continue
            prod = _producer(inp)
            if prod is None:
                continue
            if isinstance(prod, AccumulationNode):
                t = prod.tensor_ref()
                if t is not None:
                    gg = g
                    for hook in prod.hooks:
                        gg = hook(gg)
                    _accumulate_leaf(t, gg)
                continue
            # interior node: stash cotangent, decrement in-degree
            slots2 = pending.setdefault(id(prod), {})
            node_by_id[id(prod)] = prod
            oi = inp._output_index
            slots2[oi] = slots2[oi] + g if oi in slots2 else g
            indegree[id(prod)] -= 1
            if indegree[id(prod)] == 0:
                queue.append(prod)

    for cb in list(_post_backward_callbacks):
        cb()


def _zero_cotangent(av):
    import numpy as np

    if jnp.issubdtype(av.dtype, jnp.floating) or jnp.issubdtype(
        av.dtype, jnp.complexfloating
    ):
        return jnp.zeros(av.shape, av.dtype)
    return np.zeros(av.shape, dtype=jax.dtypes.float0)


def _accumulate_leaf(tensor, gdata):
    from paddle_tpu.core.tensor import Tensor

    if gdata.dtype != tensor._data.dtype:
        gdata = gdata.astype(tensor._data.dtype)
    if tensor.grad is None:
        tensor.grad = Tensor._from_data(gdata, stop_gradient=True)
    else:
        tensor.grad._data = tensor.grad._data + gdata
