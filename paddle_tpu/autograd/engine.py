"""Define-by-run autograd engine.

TPU-native analog of the reference's eager autograd
(``egr::GradNodeBase`` paddle/fluid/eager/grad_node_info.h:197,
``egr::RunBackward`` paddle/fluid/eager/backward.cc:105,
``GradTensorHolder`` grad_tensor_holder.cc, in-degree pass backward.cc:23).

Design difference from the reference: instead of hand-written/generated
per-op grad kernels, every op's backward is obtained from ``jax.vjp`` over its
XLA emitter — one autodiff rulebook (JAX's) for the whole op surface. A
GradNode stores the vjp closure (which holds XLA residual buffers, playing the
role of the reference's TensorWrapper saved tensors) and edges to the input
tensors' nodes.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "AccumulationNode", "backward", "no_grad", "enable_grad",
    "is_grad_enabled", "set_grad_enabled", "register_node", "Hook",
    "register_post_backward_callback",
]

_state = threading.local()

# Callbacks fired once after each backward() finishes draining its queue —
# the seam where the reference's EagerReducer finalizes gradient buckets
# (paddle/fluid/distributed/collective/reducer.cc FinalizeBackward).
_post_backward_callbacks: List[Callable] = []


def register_post_backward_callback(fn: Callable):
    """Register fn() to run at the end of every backward(). Returns a
    remover handle."""
    _post_backward_callbacks.append(fn)

    def remove():
        try:
            _post_backward_callbacks.remove(fn)
        except ValueError:
            pass

    return remove


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class _NoGrad(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad():
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    return _NoGrad(False)


def enable_grad():
    return _NoGrad(True)


class GradNode:
    """One recorded op in the backward graph.

    ``vjp_fn(cotangents_tuple) -> input cotangents tuple`` where cotangents
    match the op's (possibly multi-) output structure.
    """

    __slots__ = (
        "name", "vjp_fn", "inputs", "out_avals", "pending", "n_expected",
        "n_seen", "hooks", "pure_fn", "primal_datas", "__weakref__",
    )

    def __init__(
        self,
        name: str,
        vjp_fn: Callable,
        inputs: Sequence,  # list[Optional[Tensor]] — None for non-diff inputs
        out_avals: Sequence,  # list[jax.ShapeDtypeStruct] for each output
        pure_fn: Optional[Callable] = None,
        primal_datas: Optional[Sequence] = None,
    ):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        self.out_avals = list(out_avals)
        # create_graph support: the pure primal function and the primal
        # values it was recorded with. ``jax.vjp(pure_fn, *primal_datas)``
        # re-derives this node's backward differentiably, which is how
        # double grad gets real tape nodes (reference: generated
        # higher-order GradNodes, eager_gen.py; here one recursive rule).
        self.pure_fn = pure_fn
        self.primal_datas = list(primal_datas) if primal_datas is not None else None
        # filled during backward:
        self.pending: Optional[list] = None  # per-output accumulated cotangent
        self.n_expected = 0
        self.n_seen = 0
        self.hooks: List[Callable] = []

    def register_hook(self, fn: Callable):
        """fn(grads_tuple) -> grads_tuple, fired before applying vjp."""
        self.hooks.append(fn)


class AccumulationNode:
    """Terminal node for a leaf tensor; accumulates into ``tensor.grad``.

    Analog of ``egr::GradNodeAccumulation``
    (reference: paddle/fluid/eager/accumulation/accumulation_node.h).
    """

    __slots__ = ("tensor_ref", "hooks", "__weakref__")

    def __init__(self, tensor):
        import weakref

        self.tensor_ref = weakref.ref(tensor)
        self.hooks: List[Callable] = []


def register_node(outputs, name, vjp_fn, diff_inputs, pure_fn=None,
                  primal_datas=None):
    """Attach a fresh GradNode to op outputs.

    ``outputs``: list of Tensors produced by the op.
    ``diff_inputs``: list of Optional[Tensor] aligned with vjp inputs.
    ``pure_fn``/``primal_datas``: optional differentiable re-derivation of
    this node's backward (enables create_graph=True through it).
    """
    out_avals = [
        jax.ShapeDtypeStruct(o._data.shape, o._data.dtype) for o in outputs
    ]
    node = GradNode(name, vjp_fn, diff_inputs, out_avals,
                    pure_fn=pure_fn, primal_datas=primal_datas)
    for i, o in enumerate(outputs):
        if not o.stop_gradient:
            o._grad_node = node
            o._output_index = i
    return node


def _producer(tensor):
    """The node that produces ``tensor``'s gradient demand, if any."""
    if tensor is None or tensor.stop_gradient:
        return None
    node = tensor._grad_node
    if node is None:
        # leaf requiring grad -> accumulation
        acc = tensor._acc_node
        if acc is None:
            acc = AccumulationNode(tensor)
            tensor._acc_node = acc
        return acc
    return node


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, grad_targets=None):
    """Run reverse accumulation from ``tensors``.

    Mirrors ``egr::RunBackward`` (paddle/fluid/eager/backward.cc:105): build
    the in-degree map over reachable nodes, seed with the output cotangents,
    then ready-queue topological execution.

    With ``create_graph=True`` every cotangent flows as a *Tensor* and each
    node's backward is executed differentiably (a fresh GradNode is recorded
    per grad computation), so the produced gradients carry tape nodes and
    support further differentiation — the reference's double-grad contract
    (python/paddle/base/dygraph/base.py:600-630, generated higher-order
    nodes via eager_gen.py).

    ``grad_targets`` (the GeneralGrad role, paddle/fluid/eager/
    general_grad.h): when given, ``.grad`` is accumulated ONLY into those
    tensors — leaf or interior — and other leaves are left untouched.
    ``paddle.grad`` uses this so it never pollutes unrelated ``.grad``
    accumulators.
    """
    from paddle_tpu.core.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    target_ids = (
        {id(t) for t in grad_targets} if grad_targets is not None else None
    )

    # ---- seed roots -----------------------------------------------------
    roots = {}
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        node = t._grad_node
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape {tuple(t._data.shape)})"
                )
            gdata = jnp.ones_like(t._data)
        else:
            gdata = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            # keep the seed as a Tensor so downstream accumulation records
            if isinstance(g, Tensor):
                gdata = g
            else:
                gdata = Tensor._from_data(gdata, stop_gradient=True)
        if node is None:
            if target_ids is None or id(t) in target_ids:
                _accumulate_leaf(t, gdata, create_graph=create_graph)
            continue
        idx = t._output_index
        slots = roots.setdefault(node, {})
        slots[idx] = _acc_cot(slots.get(idx), gdata)

    # interior targets are captured when their PRODUCER node executes —
    # after node hooks fire, so the reported grad and the propagated grad
    # agree (and root seeds are naturally included via the node's slots)
    node_targets: dict = {}
    if grad_targets is not None:
        for t in grad_targets:
            if t is not None and t._grad_node is not None:
                node_targets.setdefault(id(t._grad_node), []).append(
                    (t._output_index, t))

    if not roots:
        return

    # ---- in-degree over reachable GradNodes ------------------------------
    indegree: dict = {}
    stack = list(roots.keys())
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen or not isinstance(node, GradNode):
            continue
        seen.add(id(node))
        indegree.setdefault(id(node), 0)
        for inp in node.inputs:
            prod = _producer(inp)
            if isinstance(prod, GradNode):
                indegree[id(prod)] = indegree.get(id(prod), 0) + 1
                stack.append(prod)

    # ---- ready-queue execution ------------------------------------------
    pending: dict = {}  # id(node) -> {out_idx: cotangent}
    node_by_id = {}
    queue = []
    for node, slots in roots.items():
        pending[id(node)] = slots
        node_by_id[id(node)] = node
        if indegree.get(id(node), 0) == 0:
            queue.append(node)

    executed = set()
    while queue:
        node = queue.pop()
        if id(node) in executed:
            continue
        executed.add(id(node))
        slots = pending.pop(id(node), {})

        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward through the graph a second time, "
                "but the saved residuals have already been freed. Pass "
                "retain_graph=True to the first backward() if you need to "
                "backward through this graph again.")

        captures = node_targets.get(id(node), ())
        if create_graph:
            in_grads = _run_node_create_graph(node, slots, captures)
        else:
            # build full cotangent tuple (zeros for outputs nobody needs;
            # int/bool outputs take float0 tangents per JAX's convention)
            cotangents = tuple(
                slots.get(i, _zero_cotangent(av))
                for i, av in enumerate(node.out_avals)
            )
            for hook in node.hooks:
                cotangents = hook(cotangents)
            for oi, t in captures:
                if _is_float_dtype(node.out_avals[oi].dtype):
                    _accumulate_leaf(t, cotangents[oi])
            in_grads = node.vjp_fn(
                cotangents if len(cotangents) > 1 else cotangents[0]
            )
            if not isinstance(in_grads, (tuple, list)):
                in_grads = (in_grads,)

        if not (retain_graph or create_graph):
            node.vjp_fn = None  # free residuals
            node.pure_fn = None
            node.primal_datas = None

        for inp, g in zip(node.inputs, in_grads):
            if inp is None:
                continue
            prod = _producer(inp)
            if prod is None:
                continue
            if isinstance(prod, AccumulationNode):
                t = prod.tensor_ref()
                if g is not None and t is not None and (
                    target_ids is None or id(t) in target_ids
                ):
                    gg = g
                    for hook in prod.hooks:
                        gg = hook(gg)
                    _accumulate_leaf(t, gg, create_graph=create_graph)
                continue
            # interior node: stash cotangent, decrement in-degree. The
            # decrement must happen even for a None grad — otherwise a
            # sibling edge's cotangent leaves the producer starved forever.
            slots2 = pending.setdefault(id(prod), {})
            node_by_id[id(prod)] = prod
            if g is not None:
                oi = inp._output_index
                slots2[oi] = _acc_cot(slots2.get(oi), g)
            indegree[id(prod)] -= 1
            if indegree[id(prod)] == 0:
                queue.append(prod)

    for cb in list(_post_backward_callbacks):
        cb()


def _is_float_dtype(d) -> bool:
    return jnp.issubdtype(d, jnp.floating) or jnp.issubdtype(
        d, jnp.complexfloating)


def _acc_cot(existing, g):
    """Accumulate a cotangent into a slot. Raw jnp arrays add directly;
    Tensors add via the registry op so create_graph accumulation is itself
    recorded on the tape (the GradTensorHolder role, grad_tensor_holder.cc)."""
    if existing is None:
        return g
    return existing + g  # Tensor.__add__ records; raw arrays add raw


def _run_node_create_graph(node, slots, captures=()):
    """Execute one node's backward differentiably.

    ``jax.vjp(grad_fn, cotangents, float_primals)`` where ``grad_fn``
    re-derives this node's vjp from its pure primal function — the produced
    input-gradients are fresh op outputs with their own GradNode (named
    ``<op>_grad``), recursively create_graph-capable (third order and up
    work the same way).
    """
    from paddle_tpu.core.tensor import Tensor

    if node.pure_fn is None or node.primal_datas is None:
        raise NotImplementedError(
            f"create_graph=True through node {node.name!r} is not "
            "supported: its backward is an opaque closure (PyLayer or "
            "custom vjp) with no differentiable re-derivation. Express the "
            "computation with differentiable paddle ops, or use "
            "create_graph=False.")

    out_avals = node.out_avals
    multi = len(out_avals) > 1
    diff_out = [i for i, av in enumerate(out_avals)
                if _is_float_dtype(av.dtype)]
    diff_out_set = set(diff_out)

    # cotangent entries in output order, Tensors at float positions
    entries = []
    for i, av in enumerate(out_avals):
        e = slots.get(i)
        if i in diff_out_set:
            if e is None:
                e = Tensor._from_data(jnp.zeros(av.shape, av.dtype),
                                      stop_gradient=True)
            elif not isinstance(e, Tensor):
                e = Tensor._from_data(e, stop_gradient=True)
        else:
            e = _zero_cotangent(av)
        entries.append(e)
    if node.hooks:
        cot = tuple(entries)
        for hook in node.hooks:
            cot = hook(cot)
        entries = [
            e if i not in diff_out_set
            else (e if isinstance(e, Tensor)
                  else Tensor._from_data(e, stop_gradient=True))
            for i, e in enumerate(cot)
        ]

    for oi, t in captures:
        if isinstance(entries[oi], Tensor):
            _accumulate_leaf(t, entries[oi], create_graph=True)

    ct_primals = [entries[i] for i in diff_out]
    n_ct = len(ct_primals)

    primal_datas = node.primal_datas
    fl_pos = [j for j, d in enumerate(primal_datas)
              if hasattr(d, "dtype") and _is_float_dtype(d.dtype)]
    fl_set = set(fl_pos)
    pure_fn = node.pure_fn

    def grad_fn(*vals):
        cts, prs = vals[:n_ct], vals[n_ct:]
        it = iter(prs)
        full_prs = [next(it) if j in fl_set else primal_datas[j]
                    for j in range(len(primal_datas))]
        _, vfn = jax.vjp(pure_fn, *full_prs)
        k = 0
        full_ct = []
        for i, av in enumerate(out_avals):
            if i in diff_out_set:
                full_ct.append(cts[k])
                k += 1
            else:
                full_ct.append(_zero_cotangent(av))
        res = vfn(tuple(full_ct) if multi else full_ct[0])
        picked = tuple(res[j] for j in fl_pos)
        # engine convention: single-output nodes return a bare array
        return picked if len(picked) != 1 else picked[0]

    vjp_primal_datas = ([t._data for t in ct_primals]
                        + [primal_datas[j] for j in fl_pos])
    out_datas, vjp2 = jax.vjp(grad_fn, *vjp_primal_datas)
    if not isinstance(out_datas, tuple):
        out_datas = (out_datas,)
    out_tensors = [Tensor._from_data(d, stop_gradient=False)
                   for d in out_datas]
    new_inputs = list(ct_primals) + [node.inputs[j] for j in fl_pos]
    register_node(out_tensors, node.name + "_grad", vjp2, new_inputs,
                  pure_fn=grad_fn, primal_datas=vjp_primal_datas)

    in_grads = [None] * len(node.inputs)
    for t, j in zip(out_tensors, fl_pos):
        in_grads[j] = t
    return in_grads


def _zero_cotangent(av):
    import numpy as np

    if _is_float_dtype(av.dtype):
        return jnp.zeros(av.shape, av.dtype)
    return np.zeros(av.shape, dtype=jax.dtypes.float0)


def _accumulate_leaf(tensor, gdata, create_graph=False):
    from paddle_tpu.core.tensor import Tensor

    gd = gdata._data if isinstance(gdata, Tensor) else gdata
    if isinstance(gd, jax.core.Tracer) and not isinstance(
            tensor._data, jax.core.Tracer):
        raise RuntimeError(
            "backward() inside a traced/compiled function would write a "
            "tracer into the .grad of a tensor that lives outside the "
            "trace (e.g. a model parameter). Use paddle.jit.TrainStep for "
            "compiled training steps, or take gradients functionally with "
            "paddle.grad over tensors created inside the traced function.")
    if create_graph and isinstance(gdata, Tensor):
        # keep the tape node on the accumulated grad (recorded cast/add)
        if gdata._data.dtype != tensor._data.dtype:
            gdata = gdata.astype(tensor._data.dtype)
        if tensor.grad is None:
            tensor.grad = gdata
        else:
            tensor.grad = tensor.grad + gdata
        return
    if isinstance(gdata, Tensor):
        gdata = gdata._data
    if gdata.dtype != tensor._data.dtype:
        gdata = gdata.astype(tensor._data.dtype)
    if tensor.grad is None:
        tensor.grad = Tensor._from_data(gdata, stop_gradient=True)
    else:
        tensor.grad._data = tensor.grad._data + gdata
