"""Autograd public API (reference: python/paddle/autograd/)."""
from paddle_tpu.autograd.engine import (  # noqa: F401
    backward, enable_grad, is_grad_enabled, no_grad, set_grad_enabled,
)
from paddle_tpu.autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from paddle_tpu.autograd.functional import grad, jacobian, hessian, vjp, jvp  # noqa: F401


class saved_tensors_hooks:
    """Reference autograd.saved_tensors_hooks packs/unpacks the tensors
    the tape saves for backward (CPU offload etc.). This build's
    backward residuals live inside XLA vjp closures and are not
    interceptable per-tensor, so the context raises rather than
    silently not firing the hooks; the TPU-native memory levers are
    jax.checkpoint via paddle_tpu.distributed.fleet.recompute and
    TrainStep's buffer donation."""

    def __init__(self, pack_hook, unpack_hook):
        self._hooks = (pack_hook, unpack_hook)

    def __enter__(self):
        raise NotImplementedError(
            "saved_tensors_hooks cannot intercept XLA vjp residuals; "
            "use recompute (activation checkpointing) for the memory-"
            "offload use case")

    def __exit__(self, *exc):
        return False
