"""Autograd public API (reference: python/paddle/autograd/)."""
from paddle_tpu.autograd.engine import (  # noqa: F401
    backward, enable_grad, is_grad_enabled, no_grad, set_grad_enabled,
)
from paddle_tpu.autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from paddle_tpu.autograd.functional import grad, jacobian, hessian, vjp, jvp  # noqa: F401
