"""Vision datasets (reference: python/paddle/vision/datasets/).

The image has no network egress, so the download-backed datasets
(CIFAR/MNIST/...) also provide a deterministic synthetic mode
(``backend='synthetic'`` or when files are absent) generating class-
conditional data — enough for pipeline/throughput work and tests; real
files are used when present at the standard paths.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["Flowers", "VOC2012", "Cifar10", "Cifar100", "MNIST", "FashionMNIST", "DatasetFolder",
           "ImageFolder", "RandomImageDataset"]


class _SyntheticImageMixin:
    def _make_synthetic(self, n, shape, num_classes, seed=0):
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, num_classes, size=n).astype(np.int64)
        # class-conditional means so models can actually learn
        means = rng.uniform(-0.5, 0.5, size=(num_classes,) + shape)
        data = (means[labels] +
                rng.normal(0, 0.25, size=(n,) + shape)).astype(np.float32)
        return data, labels


class Cifar10(Dataset, _SyntheticImageMixin):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.backend = backend or "numpy"
        n = 50000 if mode == "train" else 10000
        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/cifar/cifar-10-python.tar.gz")
        if os.path.exists(path):
            self.data, self.labels = self._load_real(path, mode)
        else:
            n_synth = min(n, 10000)
            self.data, self.labels = self._make_synthetic(
                n_synth, (3, 32, 32), self.NUM_CLASSES,
                seed=0 if mode == "train" else 1)

    def _load_real(self, path, mode):
        datas, labels = [], []
        with tarfile.open(path, "r:gz") as tf:
            # CIFAR-10 members: data_batch_1..5 / test_batch;
            # CIFAR-100 members: train / test
            if mode == "train":
                names = [m for m in tf.getmembers()
                         if "data_batch" in m.name
                         or m.name.endswith("/train")]
            else:
                names = [m for m in tf.getmembers()
                         if "test_batch" in m.name
                         or m.name.endswith("/test")]
            for m in sorted(names, key=lambda m: m.name):
                batch = pickle.load(tf.extractfile(m), encoding="bytes")
                datas.append(batch[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(batch.get(b"labels", batch.get(b"fine_labels")))
        data = (np.concatenate(datas).astype(np.float32) / 255.0)
        return data, np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img, label = self.data[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    NUM_CLASSES = 100

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        data_file = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/cifar/cifar-100-python.tar.gz")
        super().__init__(data_file, mode, transform, download, backend)


class MNIST(Dataset, _SyntheticImageMixin):
    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        n = 60000 if mode == "train" else 10000
        if image_path and os.path.exists(image_path):
            self.data, self.labels = self._load_idx(image_path, label_path)
        else:
            self.data, self.labels = self._make_synthetic(
                min(n, 10000), self.SHAPE, self.NUM_CLASSES,
                seed=2 if mode == "train" else 3)

    def _load_idx(self, image_path, label_path):
        import gzip

        with gzip.open(image_path, "rb") as f:
            f.read(16)
            data = np.frombuffer(f.read(), dtype=np.uint8)
        data = data.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0
        with gzip.open(label_path, "rb") as f:
            f.read(8)
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        return data, labels

    def __getitem__(self, idx):
        img, label = self.data[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.data)


class FashionMNIST(MNIST):
    pass


class RandomImageDataset(Dataset):
    """Pure-random benchmark dataset."""

    def __init__(self, num_samples, shape=(3, 224, 224), num_classes=1000,
                 seed=0):
        self.num_samples = num_samples
        self.shape = shape
        self.num_classes = num_classes
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.normal(0, 1, self.shape).astype(np.float32)
        label = np.int64(rng.randint(self.num_classes))
        return img, label

    def __len__(self):
        return self.num_samples


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    pass


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"), dtype=np.float32) / 255.0
    except ImportError:
        raise RuntimeError("PIL unavailable; use .npy images")


class Flowers(Dataset, _SyntheticImageMixin):
    """Oxford-102 flowers (reference vision/datasets/flowers.py): real
    archives when present (102flowers.tgz + imagelabels.mat +
    setid.mat, parsed via Pillow/scipy), synthetic class-conditional
    images otherwise."""

    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None):
        self.mode = mode
        self.transform = transform
        root = os.path.expanduser("~/.cache/paddle/dataset/flowers")
        data_file = data_file or os.path.join(root, "102flowers.tgz")
        label_file = label_file or os.path.join(root, "imagelabels.mat")
        setid_file = setid_file or os.path.join(root, "setid.mat")
        if all(os.path.exists(p) for p in
               (data_file, label_file, setid_file)):
            self._load_real(data_file, label_file, setid_file, mode)
        else:
            n = 1020 if mode == "train" else 512
            self.data, self.labels = self._make_synthetic(
                n, (3, 64, 64), self.NUM_CLASSES,
                seed=0 if mode == "train" else 1)
            self._images = None

    def _load_real(self, data_file, label_file, setid_file, mode):
        from scipy.io import loadmat

        labels = loadmat(label_file)["labels"][0]
        setid = loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        ids = setid[key][0]
        self._tar_path = data_file
        self._tar = None       # opened lazily, per process (fork-safe)
        self._tar_pid = None
        self._ids = ids
        self.labels = (labels[ids - 1] - 1).astype(np.int64)
        self.data = None
        self._images = {}

    def _get_tar(self):
        # DataLoader workers fork: a shared tarfile handle has a shared
        # file offset, so concurrent reads interleave — every process
        # opens its own handle. (r:gz re-decompresses per member; fine
        # for preprocessing, use DatasetFolder for hot loops.)
        if self._tar is None or self._tar_pid != os.getpid():
            self._tar = tarfile.open(self._tar_path, "r:gz")
            self._tar_pid = os.getpid()
        return self._tar

    def __getitem__(self, i):
        if self.data is not None:
            img, label = self.data[i], self.labels[i]
        else:
            from PIL import Image

            idx = int(self._ids[i])
            name = f"jpg/image_{idx:05d}.jpg"
            f = self._get_tar().extractfile(name)
            img = np.asarray(Image.open(f).convert("RGB"),
                             np.float32).transpose(2, 0, 1) / 255.0
            label = self.labels[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference
    vision/datasets/voc2012.py): (image, label-mask) tuples from the
    devkit tar when present, synthetic blob masks otherwise."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        data_file = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/voc2012/VOCtrainval_11-May-2012.tar")
        if os.path.exists(data_file):
            self._load_real(data_file, mode)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 64
            self.images = rng.uniform(
                0, 1, size=(n, 3, 64, 64)).astype(np.float32)
            masks = np.zeros((n, 64, 64), np.int64)
            for i in range(n):
                cy, cx = rng.randint(8, 56, 2)
                cls = rng.randint(1, 21)
                masks[i, cy - 8:cy + 8, cx - 8:cx + 8] = cls
            self.masks = masks
            self._tar = None

    def _load_real(self, data_file, mode):
        from PIL import Image  # noqa: F401 (needed at getitem)

        self._tar_path = data_file
        self._tar_pid = None
        self._tar = tarfile.open(data_file, "r")
        self._tar_pid = os.getpid()
        base = "VOCdevkit/VOC2012"
        split = {"train": "train", "valid": "val",
                 "test": "trainval"}[mode]
        lst = self._tar.extractfile(
            f"{base}/ImageSets/Segmentation/{split}.txt")
        self._names = [ln.strip().decode() for ln in lst.readlines()]
        self._base = base

    def _get_tar(self):
        if self._tar is None or self._tar_pid != os.getpid():
            self._tar = tarfile.open(self._tar_path, "r")
            self._tar_pid = os.getpid()
        return self._tar

    def __getitem__(self, i):
        if self._tar is None:
            img, mask = self.images[i], self.masks[i]
        else:
            from PIL import Image

            name = self._names[i]
            tar = self._get_tar()
            img = np.asarray(Image.open(tar.extractfile(
                f"{self._base}/JPEGImages/{name}.jpg")).convert("RGB"),
                np.float32).transpose(2, 0, 1) / 255.0
            mask = np.asarray(Image.open(tar.extractfile(
                f"{self._base}/SegmentationClass/{name}.png")),
                np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.masks) if self._tar is None else len(self._names)
