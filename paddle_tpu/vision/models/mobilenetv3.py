"""MobileNetV3 (reference: python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from paddle_tpu import nn

__all__ = ["MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcitation(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class ConvBNAct(nn.Sequential):
    def __init__(self, in_ch, out_ch, kernel=3, stride=1, groups=1,
                 act=nn.Hardswish):
        layers = [
            nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_ch),
        ]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, kernel, stride, use_se,
                 act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp_ch != in_ch:
            layers.append(ConvBNAct(in_ch, exp_ch, kernel=1, act=act))
        layers.append(ConvBNAct(exp_ch, exp_ch, kernel=kernel,
                                stride=stride, groups=exp_ch, act=act))
        if use_se:
            layers.append(SqueezeExcitation(
                exp_ch, _make_divisible(exp_ch // 4)))
        layers.append(ConvBNAct(exp_ch, out_ch, kernel=1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_RE = nn.ReLU
_HS = nn.Hardswish

# kernel, exp, out, use_se, act, stride
_LARGE = [
    (3, 16, 16, False, _RE, 1), (3, 64, 24, False, _RE, 2),
    (3, 72, 24, False, _RE, 1), (5, 72, 40, True, _RE, 2),
    (5, 120, 40, True, _RE, 1), (5, 120, 40, True, _RE, 1),
    (3, 240, 80, False, _HS, 2), (3, 200, 80, False, _HS, 1),
    (3, 184, 80, False, _HS, 1), (3, 184, 80, False, _HS, 1),
    (3, 480, 112, True, _HS, 1), (3, 672, 112, True, _HS, 1),
    (5, 672, 160, True, _HS, 2), (5, 960, 160, True, _HS, 1),
    (5, 960, 160, True, _HS, 1),
]
_SMALL = [
    (3, 16, 16, True, _RE, 2), (3, 72, 24, False, _RE, 2),
    (3, 88, 24, False, _RE, 1), (5, 96, 40, True, _HS, 2),
    (5, 240, 40, True, _HS, 1), (5, 240, 40, True, _HS, 1),
    (5, 120, 48, True, _HS, 1), (5, 144, 48, True, _HS, 1),
    (5, 288, 96, True, _HS, 2), (5, 576, 96, True, _HS, 1),
    (5, 576, 96, True, _HS, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: _make_divisible(c * scale)
        in_ch = s(16)
        feats = [ConvBNAct(3, in_ch, stride=2)]
        for k, exp, out, se, act, st in cfg:
            feats.append(InvertedResidual(in_ch, s(exp), s(out), k, st,
                                          se, act))
            in_ch = s(out)
        last_ch = s(last_exp)
        feats.append(ConvBNAct(in_ch, last_ch, kernel=1))
        self.features = nn.Sequential(*feats)
        head_ch = 1280 if last_exp == 960 else 1024
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_ch, head_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(head_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(nn.Flatten()(x))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (zero-egress build)")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (zero-egress build)")
    return MobileNetV3Small(scale=scale, **kwargs)
