"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from paddle_tpu import nn, ops

__all__ = ["InceptionV3", "inception_v3"]


def _cb(in_ch, out_ch, kernel, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, kernel, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_ch), nn.ReLU())


class InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_ch):
        super().__init__()
        self.b1 = _cb(in_ch, 64, 1)
        self.b5 = nn.Sequential(_cb(in_ch, 48, 1),
                                _cb(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_cb(in_ch, 64, 1),
                                _cb(64, 96, 3, padding=1),
                                _cb(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cb(in_ch, pool_ch, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b5(x), self.b3(x),
                           self.bp(x)], axis=1)


class InceptionB(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _cb(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_cb(in_ch, 64, 1),
                                 _cb(64, 96, 3, padding=1),
                                 _cb(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class InceptionC(nn.Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _cb(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _cb(in_ch, c7, 1), _cb(c7, c7, (1, 7), padding=(0, 3)),
            _cb(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _cb(in_ch, c7, 1), _cb(c7, c7, (7, 1), padding=(3, 0)),
            _cb(c7, c7, (1, 7), padding=(0, 3)),
            _cb(c7, c7, (7, 1), padding=(3, 0)),
            _cb(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cb(in_ch, 192, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x),
                           self.bp(x)], axis=1)


class InceptionD(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_cb(in_ch, 192, 1),
                                _cb(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _cb(in_ch, 192, 1), _cb(192, 192, (1, 7), padding=(0, 3)),
            _cb(192, 192, (7, 1), padding=(3, 0)),
            _cb(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class InceptionE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _cb(in_ch, 320, 1)
        self.b3_stem = _cb(in_ch, 384, 1)
        self.b3_a = _cb(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _cb(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = nn.Sequential(_cb(in_ch, 448, 1),
                                     _cb(448, 384, 3, padding=1))
        self.bd_a = _cb(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _cb(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cb(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.bd_stem(x)
        return ops.concat([
            self.b1(x),
            ops.concat([self.b3_a(s), self.b3_b(s)], axis=1),
            ops.concat([self.bd_a(d), self.bd_b(d)], axis=1),
            self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _cb(3, 32, 3, stride=2), _cb(32, 32, 3),
            _cb(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _cb(64, 80, 1), _cb(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(nn.Flatten()(x)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (zero-egress build)")
    return InceptionV3(**kwargs)
