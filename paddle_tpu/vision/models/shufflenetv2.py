"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from paddle_tpu import nn, ops

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = ops.reshape(x, [b, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [b, c, h, w])


def _conv_bn(in_ch, out_ch, kernel, stride=1, groups=1, act=nn.ReLU):
    layers = [nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                        padding=kernel // 2, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act is not None:
        layers.append(act())
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(branch, branch, 1, act=act),
                _conv_bn(branch, branch, 3, stride=1, groups=branch,
                         act=None),
                _conv_bn(branch, branch, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(in_ch, in_ch, 3, stride=stride, groups=in_ch,
                         act=None),
                _conv_bn(in_ch, branch, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn(in_ch, branch, 1, act=act),
                _conv_bn(branch, branch, 3, stride=stride, groups=branch,
                         act=None),
                _conv_bn(branch, branch, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1 = x[:, :c]
            x2 = x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        act_layer = nn.ReLU if act == "relu" else nn.Swish
        chs = _STAGE_OUT[scale]
        self.conv1 = _conv_bn(3, chs[0], 3, stride=2, act=act_layer)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = chs[0]
        for i, repeat in enumerate([4, 8, 4]):
            out_ch = chs[i + 1]
            seq = [InvertedResidual(in_ch, out_ch, 2, act_layer)]
            seq += [InvertedResidual(out_ch, out_ch, 1, act_layer)
                    for _ in range(repeat - 1)]
            stages.append(nn.Sequential(*seq))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(in_ch, chs[4], 1, act=act_layer)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten()(x))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (zero-egress build)")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _shufflenet(0.25, pretrained=pretrained, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _shufflenet(0.33, pretrained=pretrained, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _shufflenet(0.5, pretrained=pretrained, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _shufflenet(1.0, pretrained=pretrained, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _shufflenet(1.5, pretrained=pretrained, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _shufflenet(2.0, pretrained=pretrained, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kw)
