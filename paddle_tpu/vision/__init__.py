"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from paddle_tpu.vision import datasets, models, transforms  # noqa: F401
