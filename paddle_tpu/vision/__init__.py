"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """Reference vision.image.set_image_backend ('pil' | 'cv2')."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file via the configured backend (reference
    vision.image.image_load)."""
    b = backend or _image_backend
    if b not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {b!r}")
    if b == "cv2":
        try:
            import cv2

            return cv2.imread(path)
        except ImportError as e:
            raise ImportError(
                "cv2 is not installed; use the 'pil' backend") from e
    from PIL import Image

    return Image.open(path)
