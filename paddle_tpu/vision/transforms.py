"""Vision transforms on numpy CHW arrays (reference:
python/paddle/vision/transforms/)."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Transpose", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip", "Pad",
           "RandomResizedCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        if img.max() > 1.5:
            img = img / 255.0
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp

        img = np.asarray(img, dtype=np.float32)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if chw:
            c, h, w = img.shape
            out = jax.image.resize(jnp.asarray(img),
                                   (c, self.size[0], self.size[1]),
                                   method="linear")
        else:
            h, w, c = img.shape
            out = jax.image.resize(jnp.asarray(img),
                                   (self.size[0], self.size[1], c),
                                   method="linear")
        return np.asarray(out)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = [(0, 0), (p, p), (p, p)] if chw else [(p, p), (p, p),
                                                         (0, 0)]
            img = np.pad(img, pads, mode="constant")
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th,
                                                          j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th,
                                                          j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = img[:, i:i + th, j:j + tw] if chw else \
                    img[i:i + th, j:j + tw]
                return self._resize(crop)
        return self._resize(img)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            img = np.asarray(img)
            chw = img.shape[0] in (1, 3, 4)
            return img[..., ::-1].copy() if chw else img[:, ::-1].copy()
        return img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        p = self.padding
        chw = img.shape[0] in (1, 3, 4)
        pads = [(0, 0), (p, p), (p, p)] if chw else [(p, p), (p, p), (0, 0)]
        return np.pad(img, pads, mode="constant")
