"""Vision transforms on numpy CHW arrays (reference:
python/paddle/vision/transforms/)."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Transpose", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip", "Pad",
           "RandomResizedCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        if img.max() > 1.5:
            img = img / 255.0
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp

        img = np.asarray(img, dtype=np.float32)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if chw:
            c, h, w = img.shape
            out = jax.image.resize(jnp.asarray(img),
                                   (c, self.size[0], self.size[1]),
                                   method="linear")
        else:
            h, w, c = img.shape
            out = jax.image.resize(jnp.asarray(img),
                                   (self.size[0], self.size[1], c),
                                   method="linear")
        return np.asarray(out)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = [(0, 0), (p, p), (p, p)] if chw else [(p, p), (p, p),
                                                         (0, 0)]
            img = np.pad(img, pads, mode="constant")
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th,
                                                          j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th,
                                                          j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0],
                                                         img.shape[1])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = img[:, i:i + th, j:j + tw] if chw else \
                    img[i:i + th, j:j + tw]
                return self._resize(crop)
        return self._resize(img)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            img = np.asarray(img)
            chw = img.shape[0] in (1, 3, 4)
            return img[..., ::-1].copy() if chw else img[:, ::-1].copy()
        return img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        p = self.padding
        chw = img.shape[0] in (1, 3, 4)
        pads = [(0, 0), (p, p), (p, p)] if chw else [(p, p), (p, p), (0, 0)]
        return np.pad(img, pads, mode="constant")


# ---------------------------------------------------------------------------
# color / photometric ops (reference transforms.py BrightnessTransform:
# ContrastTransform/SaturationTransform/HueTransform/ColorJitter/
# Grayscale; host-side preprocessing, so numpy like the rest)
# ---------------------------------------------------------------------------

def _as_chw(img):
    """Match the file's dual-layout convention (chw = shape[0] in
    1/3/4): return (CHW float array, layout tag)."""
    img = np.asarray(img, dtype=np.float32)
    if img.ndim == 2:
        return img[None], "hw"
    if img.shape[0] in (1, 3, 4):
        return img, "chw"
    return img.transpose(2, 0, 1), "hwc"


def _restore(img, fmt):
    if fmt == "hw":
        return img[0]
    if fmt == "hwc":
        return img.transpose(1, 2, 0)
    return img


def _chw_float(img):
    img, fmt = _as_chw(img)
    scale = 255.0 if img.max() > 1.5 else 1.0
    return img / scale, scale, fmt


def _rand_factor(delta):
    return float(np.random.uniform(max(0.0, 1.0 - delta), 1.0 + delta))


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        x, scale, fmt = _chw_float(img)
        out = np.clip(x * _rand_factor(self.value), 0.0, 1.0)
        return _restore(out * scale, fmt)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        x, scale, fmt = _chw_float(img)
        mean = x.mean()
        out = np.clip((x - mean) * _rand_factor(self.value) + mean,
                      0.0, 1.0)
        return _restore(out * scale, fmt)


class SaturationTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        x, scale, fmt = _chw_float(img)
        gray = (0.299 * x[0] + 0.587 * x[1] + 0.114 * x[2])[None]
        out = np.clip(gray + (x - gray) * _rand_factor(self.value),
                      0.0, 1.0)
        return _restore(out * scale, fmt)


def _rgb_to_hsv(x):
    r, g, b = x[0], x[1], x[2]
    mx = np.max(x, axis=0)
    mn = np.min(x, axis=0)
    d = mx - mn
    h = np.zeros_like(mx)
    nz = d > 1e-8
    idx = nz & (mx == r)
    h[idx] = ((g - b)[idx] / d[idx]) % 6
    idx = nz & (mx == g)
    h[idx] = (b - r)[idx] / d[idx] + 2
    idx = nz & (mx == b)
    h[idx] = (r - g)[idx] / d[idx] + 4
    h = h / 6.0
    s = np.where(mx > 1e-8, d / np.maximum(mx, 1e-8), 0.0)
    return np.stack([h, s, mx])


def _hsv_to_rgb(x):
    h, s, v = x[0] * 6.0, x[1], x[2]
    i = np.floor(h).astype(np.int32) % 6
    f = h - np.floor(h)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b])


class HueTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)  # in [0, 0.5]

    def __call__(self, img):
        x, scale, fmt = _chw_float(img)
        hsv = _rgb_to_hsv(x)
        shift = float(np.random.uniform(-self.value, self.value))
        hsv[0] = (hsv[0] + shift) % 1.0
        return _restore(np.clip(_hsv_to_rgb(hsv), 0.0, 1.0) * scale,
                        fmt)


class ColorJitter:
    """reference transforms.py ColorJitter: random order of the four
    photometric jitters."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0, keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        for i in np.random.permutation(len(self.ts)):
            img = self.ts[i](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def __call__(self, img):
        x, fmt = _as_chw(img)
        gray = (0.299 * x[0] + 0.587 * x[1] + 0.114 * x[2])[None]
        return _restore(np.repeat(gray, self.n, axis=0), fmt)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            img = np.asarray(img)
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            # vertical = flip the HEIGHT axis in either layout
            return (img[:, ::-1, :] if chw else img[::-1]).copy()
        return np.asarray(img)


# ---------------------------------------------------------------------------
# geometric warps (inverse-map bilinear resampling; reference uses cv2/
# PIL backends — same math)
# ---------------------------------------------------------------------------

def _warp_affine(img, mat, fill=0.0):
    """img: CHW; mat: 2x3 OUTPUT->INPUT affine (inverse map)."""
    from scipy import ndimage

    c, h, w = img.shape
    out = np.empty_like(img, dtype=np.float32)
    for ci in range(c):
        out[ci] = ndimage.affine_transform(
            img[ci].astype(np.float32), mat[:, :2], offset=mat[:, 2],
            output_shape=(h, w), order=1, mode="constant", cval=fill)
    return out


def _center_affine(h, w, angle_deg, translate, scale, shear_deg):
    """Build the OUTPUT->INPUT matrix for rotate/translate/scale/shear
    about the image center."""
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    a = np.deg2rad(angle_deg)
    sx = np.deg2rad(shear_deg[0])
    sy = np.deg2rad(shear_deg[1])
    # forward: T(center) R S Shear T(-center) + translate
    rs = np.asarray([
        [np.cos(a + sy), -np.sin(a + sx)],
        [np.sin(a + sy), np.cos(a + sx)],
    ]) * scale
    # operate in (y, x): build the full forward matrix, then invert
    fwd = np.eye(3)
    fwd[:2, :2] = rs
    fwd[0, 2] = cy - rs[0, 0] * cy - rs[0, 1] * cx + translate[1]
    fwd[1, 2] = cx - rs[1, 0] * cy - rs[1, 1] * cx + translate[0]
    bwd = np.linalg.inv(fwd)
    return bwd[:2, :]


class RandomRotation:
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        if np.isscalar(degrees):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def __call__(self, img):
        x, fmt = _as_chw(img)
        ang = float(np.random.uniform(*self.degrees))
        m = _center_affine(x.shape[1], x.shape[2], ang, (0, 0), 1.0,
                           (0, 0))
        return _restore(_warp_affine(x, m, fill=self.fill), fmt)


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if np.isscalar(degrees):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill

    def __call__(self, img):
        x, fmt = _as_chw(img)
        h, w = x.shape[1:]
        ang = float(np.random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = float(np.random.uniform(-self.translate[0],
                                         self.translate[0]) * w)
            ty = float(np.random.uniform(-self.translate[1],
                                         self.translate[1]) * h)
        sc = 1.0 if self.scale is None else \
            float(np.random.uniform(*self.scale))
        sh = (0.0, 0.0)
        if self.shear is not None:
            shd = self.shear if not np.isscalar(self.shear) \
                else (-abs(self.shear), abs(self.shear))
            sh = (float(np.random.uniform(shd[0], shd[1])), 0.0)
        m = _center_affine(h, w, ang, (tx, ty), sc, sh)
        return _restore(_warp_affine(x, m, fill=self.fill), fmt)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def __call__(self, img):
        from scipy import ndimage

        x, fmt = _as_chw(img)
        if np.random.rand() >= self.prob:
            return _restore(x, fmt)
        c, h, w = x.shape
        d = self.distortion_scale
        dx = w * d / 2.0
        dy = h * d / 2.0
        src = np.asarray([[0, 0], [0, w - 1], [h - 1, 0],
                          [h - 1, w - 1]], np.float64)
        dst = src + np.stack([
            np.random.uniform(-dy, dy, 4),
            np.random.uniform(-dx, dx, 4)], axis=1)
        # homography dst->src (inverse map): solve 8-dof DLT
        A, b = [], []
        for (ys, xs), (yd, xd) in zip(src, dst):
            A.append([yd, xd, 1, 0, 0, 0, -ys * yd, -ys * xd])
            b.append(ys)
            A.append([0, 0, 0, yd, xd, 1, -xs * yd, -xs * xd])
            b.append(xs)
        p = np.linalg.solve(np.asarray(A), np.asarray(b))
        H = np.asarray([[p[0], p[1], p[2]], [p[3], p[4], p[5]],
                        [p[6], p[7], 1.0]])
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        ones = np.ones_like(yy, np.float64)
        pts = np.stack([yy, xx, ones]).reshape(3, -1)
        mapped = H @ pts
        my = (mapped[0] / mapped[2]).reshape(h, w)
        mx = (mapped[1] / mapped[2]).reshape(h, w)
        out = np.empty_like(x)
        for ci in range(c):
            out[ci] = ndimage.map_coordinates(
                x[ci], [my, mx], order=1, mode="constant",
                cval=self.fill)
        return _restore(out, fmt)


class RandomErasing:
    """reference transforms.py RandomErasing (cutout with random box)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        x, fmt = _as_chw(img)
        x = x.copy()
        if np.random.rand() >= self.prob:
            return _restore(x, fmt)
        c, h, w = x.shape
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if self.value == "random":
                    x[:, i:i + eh, j:j + ew] = np.random.normal(
                        size=(c, eh, ew))
                else:
                    x[:, i:i + eh, j:j + ew] = self.value
                return _restore(x, fmt)
        return _restore(x, fmt)


__all__ += ["BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "ColorJitter",
            "Grayscale", "RandomVerticalFlip", "RandomRotation",
            "RandomAffine", "RandomPerspective", "RandomErasing"]


# ---------------------------------------------------------------------------
# functional API + BaseTransform (reference
# python/paddle/vision/transforms/functional.py and transforms.py
# BaseTransform) — each functional reuses the class implementations'
# helpers so the two surfaces cannot diverge.
# ---------------------------------------------------------------------------

class BaseTransform:
    """Reference BaseTransform: subclasses implement _apply_image (and
    optionally _apply_* for other keys); __call__ routes per key."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        outs = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            outs.append(fn(data) if fn else data)
        return tuple(outs)


def to_tensor(pic, data_format="CHW"):
    """PIL/ndarray -> float tensor in [0, 1] (reference F.to_tensor)."""
    from paddle_tpu.core.tensor import Tensor

    raw = np.asarray(pic)
    arr = raw.astype(np.float32)
    if raw.dtype == np.uint8:
        arr = arr / 255.0  # dtype-keyed, like the reference (a dark
        # uint8 image must scale the same as a bright one)
    if arr.ndim == 2:
        arr = arr[..., None]
    if data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    if chw:
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def hflip(img):
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return arr[..., ::-1].copy() if chw else arr[:, ::-1].copy()


def vflip(img):
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return arr[:, ::-1].copy() if chw else arr[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def adjust_brightness(img, brightness_factor):
    x, fmt = _as_chw(np.asarray(img))
    return _restore(np.clip(x * brightness_factor, 0.0, 1.0), fmt)


def adjust_contrast(img, contrast_factor):
    x, fmt = _as_chw(np.asarray(img))
    mean = x.mean()
    return _restore(np.clip(mean + contrast_factor * (x - mean),
                            0.0, 1.0), fmt)


def adjust_hue(img, hue_factor):
    x, fmt = _as_chw(np.asarray(img))
    hsv = _rgb_to_hsv(x)
    hsv[0] = (hsv[0] + hue_factor) % 1.0
    return _restore(np.clip(_hsv_to_rgb(hsv), 0.0, 1.0), fmt)


def rotate(img, angle, interpolation="nearest", expand=False,
           center=None, fill=0):
    x, fmt = _as_chw(np.asarray(img))
    m = _center_affine(x.shape[1], x.shape[2], float(angle), (0, 0),
                       1.0, (0, 0))
    return _restore(_warp_affine(x, m, fill=fill), fmt)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    x, fmt = _as_chw(np.asarray(img))
    sh = shear if isinstance(shear, (list, tuple)) else (shear, 0.0)
    m = _center_affine(x.shape[1], x.shape[2], float(angle),
                       tuple(translate), float(scale), tuple(sh))
    return _restore(_warp_affine(x, m, fill=fill), fmt)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Reference F.perspective: warp so startpoints map to endpoints."""
    x, fmt = _as_chw(np.asarray(img))
    # _warp_perspective maps OUTPUT pixels back to source positions, so
    # it needs the inverse transform: solve startpoints <- endpoints
    mat = _perspective_coeffs(startpoints, endpoints)
    out = _warp_perspective(x, mat, fill=fill)
    return _restore(out, fmt)


def _perspective_coeffs(src, dst):
    a = []
    b = []
    for (sx, sy), (dx, dy) in zip(src, dst):
        a.append([dx, dy, 1, 0, 0, 0, -sx * dx, -sx * dy])
        a.append([0, 0, 0, dx, dy, 1, -sy * dx, -sy * dy])
        b.extend([sx, sy])
    coef = np.linalg.solve(np.asarray(a, np.float64),
                           np.asarray(b, np.float64))
    return np.append(coef, 1.0).reshape(3, 3)


def _warp_perspective(img, mat, fill=0.0):
    c, h, w = img.shape
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones]).reshape(3, -1).astype(np.float64)
    src = mat @ pts
    sx = src[0] / src[2]
    sy = src[1] / src[2]
    valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)
    sxc = np.clip(np.round(sx), 0, w - 1).astype(np.int64)
    syc = np.clip(np.round(sy), 0, h - 1).astype(np.int64)
    out = img[:, syc, sxc]
    out = np.where(valid[None], out, fill)
    return out.reshape(c, h, w)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def erase(img, i, j, h, w, v, inplace=False):
    """Zero/fill a region (reference F.erase); v is the fill value."""
    from paddle_tpu.core.tensor import Tensor as _T

    is_tensor = isinstance(img, _T)
    arr = np.array(img.numpy() if is_tensor else img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    if chw:
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return _T(arr) if is_tensor else arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (arr - mean) / std


__all__ += ["BaseTransform", "to_tensor", "resize", "crop",
            "center_crop", "hflip", "vflip", "pad",
            "adjust_brightness", "adjust_contrast", "adjust_hue",
            "rotate", "affine", "perspective", "to_grayscale", "erase",
            "normalize"]
