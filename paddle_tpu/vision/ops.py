"""paddle.vision.ops — detection/vision operators.

Reference: python/paddle/vision/ops.py (yolo_loss:58, yolo_box:266,
prior_box:427, box_coder:573, deform_conv2d:753, DeformConv2D:960,
distribute_fpn_proposals:1156, psroi_pool:1393, roi_pool:1514,
roi_align:1640, ConvNormActivation:1810, nms:1867,
generate_proposals:2038, matrix_nms:2236).

TPU-native split:
* Differentiable feature ops (roi_align/roi_pool/psroi_pool/
  deform_conv2d) are registry emitters (ops/vision_ops.py): pure JAX
  gather+matmul graphs, autograd via the registry's vjp, static shapes
  → jit/Program-mode safe.
* Post-processing (nms/matrix_nms/generate_proposals/
  distribute_fpn_proposals) returns data-dependent-sized results, so
  these run eagerly: device compute for the O(n²) IoU/suppression math,
  host-side boolean indexing for the final variable-length selection —
  same split the reference uses (CUDA kernel + host copy_back). Inside
  a compiled region, use the fixed-size mask/score outputs instead.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer

__all__ = [
    "yolo_loss", "yolo_box", "prior_box", "box_coder", "deform_conv2d",
    "DeformConv2D", "distribute_fpn_proposals", "psroi_pool", "PSRoIPool",
    "roi_pool", "RoIPool", "roi_align", "RoIAlign", "nms", "matrix_nms",
    "generate_proposals", "ConvNormActivation", "read_file", "decode_jpeg",
]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(d):
    return Tensor._from_data(d)


def _boxes_to_flat(boxes, boxes_num):
    """Reference RoI ops take per-image box counts (LoD); the TPU ops
    take a flat (R,4) + (R,) image index — convert host-side."""
    bn = np.asarray(_data(boxes_num)).astype(np.int64)
    idx = np.repeat(np.arange(len(bn)), bn)
    return jnp.asarray(idx, jnp.int32)


# ---------------------------------------------------------------------------
# RoI family + deformable conv (registry ops)
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    from paddle_tpu import ops

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    idx = _boxes_to_flat(boxes, boxes_num)
    return ops.roi_align(x, boxes, _wrap(idx),
                         output_size=tuple(output_size),
                         spatial_scale=float(spatial_scale),
                         sampling_ratio=int(sampling_ratio),
                         aligned=bool(aligned))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    from paddle_tpu import ops

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    idx = _boxes_to_flat(boxes, boxes_num)
    return ops.roi_pool(x, boxes, _wrap(idx),
                        output_size=tuple(output_size),
                        spatial_scale=float(spatial_scale))


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    from paddle_tpu import ops

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    idx = _boxes_to_flat(boxes, boxes_num)
    return ops.psroi_pool(x, boxes, _wrap(idx),
                          output_size=tuple(output_size),
                          spatial_scale=float(spatial_scale))


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    from paddle_tpu import ops

    return ops.deform_conv2d(x, offset, weight, mask, bias,
                             stride=stride, padding=padding,
                             dilation=dilation,
                             deformable_groups=deformable_groups,
                             groups=groups)


class DeformConv2D(Layer):
    """Deformable conv layer (reference vision/ops.py:960). v1 when
    forward gets no mask, v2 (modulated) with one."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        import math as _m

        from paddle_tpu.nn.initializer import Uniform

        fan_in = in_channels * kernel_size[0] * kernel_size[1] // groups
        bound = 1.0 / _m.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *kernel_size],
            default_initializer=Uniform(-bound, bound))
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels],
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation,
                             deformable_groups=self._deformable_groups,
                             groups=self._groups, mask=mask)


# ---------------------------------------------------------------------------
# box codecs / anchors (pure broadcast math — jit-safe)
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against anchors (reference vision/ops.py:573,
    phi/kernels/gpu/box_coder_kernel.cu)."""
    pb = _data(prior_box).astype(jnp.float32)
    tb = _data(target_box).astype(jnp.float32)
    if prior_box_var is None:
        pbv = jnp.ones((4,), jnp.float32)
    elif isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
    else:
        pbv = _data(prior_box_var).astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0

    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5

    if code_type == "encode_center_size":
        # tb: (M, 4) targets vs each prior: out (M, N, 4)
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        out = out / (pbv.reshape(1, -1, 4) if pbv.ndim == 2
                     else pbv.reshape(1, 1, 4))
        return _wrap(out)
    elif code_type == "decode_center_size":
        # tb: (N, M, 4) deltas; priors broadcast along `axis`
        var = pbv if pbv.ndim == 1 else pbv
        if pbv.ndim == 2:
            var = pbv[:, None, :] if axis == 0 else pbv[None, :, :]
        else:
            var = pbv.reshape(1, 1, 4)
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
        else:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
        d = tb * var
        ocx = d[..., 0] * pw_ + pcx_
        ocy = d[..., 1] * ph_ + pcy_
        ow = jnp.exp(d[..., 2]) * pw_
        oh = jnp.exp(d[..., 3]) * ph_
        out = jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                         ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm],
                        axis=-1)
        return _wrap(out)
    raise ValueError(f"unknown code_type {code_type!r}")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (reference vision/ops.py:427). Anchor
    geometry is shape-only → computed host-side in numpy, returned as
    device constants."""
    _, _, fh, fw = _data(input).shape
    _, _, ih, iw = _data(image).shape
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    boxes = []
    vars_ = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                ms = float(ms)
                if min_max_aspect_ratios_order:
                    cell.append((cx, cy, ms, ms))
                    if max_sizes:
                        sq = np.sqrt(ms * float(max_sizes[k]))
                        cell.append((cx, cy, sq, sq))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                else:
                    for ar in ars:
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                    if max_sizes:
                        sq = np.sqrt(ms * float(max_sizes[k]))
                        cell.append((cx, cy, sq, sq))
            for (ccx, ccy, w, h) in cell:
                boxes.append(((ccx - w / 2) / iw, (ccy - h / 2) / ih,
                              (ccx + w / 2) / iw, (ccy + h / 2) / ih))
                vars_.append(variance)
    n_per_cell = len(boxes) // (fh * fw)
    b = np.asarray(boxes, np.float32).reshape(fh, fw, n_per_cell, 4)
    if clip:
        b = np.clip(b, 0.0, 1.0)
    v = np.asarray(vars_, np.float32).reshape(fh, fw, n_per_cell, 4)
    return _wrap(jnp.asarray(b)), _wrap(jnp.asarray(v))


# ---------------------------------------------------------------------------
# YOLO head (pure math — jit-safe)
# ---------------------------------------------------------------------------

def _yolo_grid(x, anchors, class_num, downsample_ratio, scale_x_y):
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
    p = x.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + gx) / w
    by = (jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + gy) / h
    bw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / (
        w * downsample_ratio)
    bh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / (
        h * downsample_ratio)
    conf = jax.nn.sigmoid(p[:, :, 4])
    cls = jax.nn.sigmoid(p[:, :, 5:])
    return bx, by, bw, bh, conf, cls


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head into boxes+scores (reference
    vision/ops.py:266). Fixed-size outputs (thresholding zeroes scores
    instead of dropping rows) → jit-safe."""
    xd = _data(x).astype(jnp.float32)
    imgs = _data(img_size).astype(jnp.float32)
    if iou_aware:
        n, c, h, w = xd.shape
        na = len(anchors) // 2
        ioup = jax.nn.sigmoid(xd[:, :na])
        xd = xd[:, na:]
    bx, by, bw, bh, conf, cls = _yolo_grid(
        xd, anchors, class_num, downsample_ratio, scale_x_y)
    if iou_aware:
        conf = conf ** (1.0 - iou_aware_factor) * \
            ioup ** iou_aware_factor
    n, na, h, w = conf.shape
    ih = imgs[:, 0].reshape(n, 1, 1, 1)
    iw = imgs[:, 1].reshape(n, 1, 1, 1)
    x1 = (bx - bw * 0.5) * iw
    y1 = (by - bh * 0.5) * ih
    x2 = (bx + bw * 0.5) * iw
    y2 = (by + bh * 0.5) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, iw - 1)
        y1 = jnp.clip(y1, 0.0, ih - 1)
        x2 = jnp.clip(x2, 0.0, iw - 1)
        y2 = jnp.clip(y2, 0.0, ih - 1)
    keep = (conf > conf_thresh).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = cls * (conf * keep)[:, :, None]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w,
                                                     class_num)
    return _wrap(boxes), _wrap(scores)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference vision/ops.py:58). Routed through
    the registry (emitter in ops/vision_ops.py) so autograd records the
    vjp — differentiable end-to-end."""
    from paddle_tpu import ops

    return ops.yolo_loss(x, gt_box, gt_label, gt_score,
                         anchors=tuple(anchors),
                         anchor_mask=tuple(anchor_mask),
                         class_num=int(class_num),
                         ignore_thresh=float(ignore_thresh),
                         downsample_ratio=int(downsample_ratio),
                         use_label_smooth=bool(use_label_smooth),
                         scale_x_y=float(scale_x_y))


# ---------------------------------------------------------------------------
# NMS family (eager: variable-length outputs)
# ---------------------------------------------------------------------------

def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    iw = jnp.maximum(jnp.minimum(x2[:, None], x2[None, :])
                     - jnp.maximum(x1[:, None], x1[None, :]), 0)
    ih = jnp.maximum(jnp.minimum(y2[:, None], y2[None, :])
                     - jnp.maximum(y1[:, None], y1[None, :]), 0)
    inter = iw * ih
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                               1e-10)


def _nms_keep_mask(boxes, iou_threshold):
    """Greedy NMS as a fixed-trip-count device loop: boxes must already
    be sorted by descending score. Returns a (R,) bool keep mask."""
    r = boxes.shape[0]
    iou = _iou_matrix(boxes)

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & keep[i] & \
            (jnp.arange(r) > i)
        return keep & ~sup

    return jax.lax.fori_loop(0, r, body, jnp.ones((r,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy (optionally per-category) NMS (reference
    vision/ops.py:1867). Suppression runs on device; the final
    variable-length index selection is host-side — eager only."""
    bd = _data(boxes).astype(jnp.float32)
    r = bd.shape[0]
    if scores is None:
        keep = np.asarray(_nms_keep_mask(bd, iou_threshold))
        return _wrap(jnp.asarray(np.nonzero(keep)[0].astype(np.int64)))
    sd = _data(scores).astype(jnp.float32)
    order = jnp.argsort(-sd)
    if category_idxs is not None:
        # per-category: offset boxes by category so cross-category pairs
        # never overlap (the standard batched-NMS trick)
        cd = _data(category_idxs).astype(jnp.float32)
        span = (bd.max() - bd.min()) + 1.0
        bd_off = bd + (cd * span)[:, None]
    else:
        bd_off = bd
    keep_sorted = _nms_keep_mask(bd_off[order], iou_threshold)
    kept = np.asarray(order)[np.asarray(keep_sorted)]
    if top_k is not None:
        kept = kept[:top_k]
    return _wrap(jnp.asarray(kept.astype(np.int64)))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2., background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py:2236; SOLOv2 paper): decay
    every score by the max-IoU overlap with higher-scored same-class
    boxes — no sequential suppression, so the whole thing is one
    batched device computation (TPU-friendly), with host-side
    thresholding at the end."""
    bd = _data(bboxes).astype(jnp.float32)    # (N, M, 4)
    sd = _data(scores).astype(jnp.float32)    # (N, C, M)
    n, c, m = sd.shape
    outs, idxs, nums = [], [], []
    for b in range(n):
        cls_ids, box_ids, final = [], [], []
        flat_scores = []
        for ci in range(c):
            if ci == background_label:
                continue
            s = sd[b, ci]
            sel = np.asarray(s > score_threshold).nonzero()[0]
            if sel.size == 0:
                continue
            s_sel = np.asarray(s)[sel]
            order = np.argsort(-s_sel)[:nms_top_k]
            sel = sel[order]
            bx = bd[b][jnp.asarray(sel)]
            iou = np.array(_iou_matrix(bx))  # writable copy
            np.fill_diagonal(iou, 0.0)
            iou = np.triu(iou)  # iou[i,j]: box j vs higher-scored box i
            comp = iou.max(axis=0)  # per-box max IoU with higher-scored
            # decay_j = min_i f(iou_ij)/f(comp_i): each suppressor i is
            # compensated by its own overlap with boxes above it
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / np.maximum(1 - comp[:, None],
                                                1e-10)).min(axis=0)
            dec_scores = np.asarray(s)[sel] * decay
            keep = dec_scores >= post_threshold
            for k in np.nonzero(keep)[0]:
                cls_ids.append(ci)
                box_ids.append(int(sel[k]))
                flat_scores.append(float(dec_scores[k]))
        order = np.argsort(-np.asarray(flat_scores)) if flat_scores \
            else np.array([], np.int64)
        order = order[:keep_top_k]
        rows = [[float(cls_ids[i]), flat_scores[i],
                 *np.asarray(bd[b][box_ids[i]]).tolist()] for i in order]
        outs.append(np.asarray(rows, np.float32).reshape(-1, 6))
        idxs.extend(int(b * m + box_ids[i]) for i in order)
        nums.append(len(order))
    out = _wrap(jnp.asarray(np.concatenate(outs, axis=0)
                            if outs else np.zeros((0, 6), np.float32)))
    ret = [out]
    if return_index:
        ret.append(_wrap(jnp.asarray(np.asarray(idxs, np.int64))))
    if return_rois_num:
        ret.append(_wrap(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(ret) if len(ret) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference vision/ops.py:2038): decode
    anchor deltas, clip, filter tiny boxes, NMS — per image, eager."""
    sd = np.asarray(_data(scores))            # (N, A, H, W)
    dd = np.asarray(_data(bbox_deltas))       # (N, 4A, H, W)
    iszs = np.asarray(_data(img_size))        # (N, 2) (h, w)
    an = np.asarray(_data(anchors)).reshape(-1, 4)
    va = np.asarray(_data(variances)).reshape(-1, 4)
    n = sd.shape[0]
    offset = 1.0 if pixel_offset else 0.0
    all_rois, all_scores, nums = [], [], []
    for b in range(n):
        s = sd[b].transpose(1, 2, 0).reshape(-1)
        d = dd[b].reshape(-1, 4, sd.shape[2], sd.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], va[order]
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        props = np.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - offset, cy + h * 0.5 - offset],
                         axis=1)
        ih, iw = iszs[b][0], iszs[b][1]
        props[:, 0] = props[:, 0].clip(0, iw - offset)
        props[:, 1] = props[:, 1].clip(0, ih - offset)
        props[:, 2] = props[:, 2].clip(0, iw - offset)
        props[:, 3] = props[:, 3].clip(0, ih - offset)
        ws = props[:, 2] - props[:, 0] + offset
        hs = props[:, 3] - props[:, 1] + offset
        keep = (ws >= min_size) & (hs >= min_size)
        props, s = props[keep], s[keep]
        if props.shape[0]:
            km = np.asarray(_nms_keep_mask(jnp.asarray(props),
                                           nms_thresh))
            sel = np.nonzero(km)[0][:post_nms_top_n]
            props, s = props[sel], s[sel]
        all_rois.append(props.astype(np.float32))
        all_scores.append(s.astype(np.float32))
        nums.append(props.shape[0])
    rois = _wrap(jnp.asarray(np.concatenate(all_rois, axis=0)))
    rscores = _wrap(jnp.asarray(np.concatenate(all_scores, axis=0)))
    if return_rois_num:
        return rois, rscores, _wrap(jnp.asarray(np.asarray(nums,
                                                           np.int32)))
    return rois, rscores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (reference vision/ops.py:1156)
    — host-side grouping (variable-size splits)."""
    rois = np.asarray(_data(fpn_rois))
    offset = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + offset
    h = rois[:, 3] - rois[:, 1] + offset
    scale = np.sqrt(np.maximum(w * h, 0.0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    n_levels = max_level - min_level + 1
    outs, restore = [], []
    order = []
    for li in range(n_levels):
        idx = np.nonzero(lvl == min_level + li)[0]
        outs.append(_wrap(jnp.asarray(rois[idx].astype(np.float32))))
        order.extend(idx.tolist())
    restore_idx = np.empty(len(order), np.int64)
    restore_idx[np.asarray(order, np.int64)] = np.arange(len(order))
    rois_num_per_level = None
    if rois_num is not None:
        rn = np.asarray(_data(rois_num))
        img_of = np.repeat(np.arange(len(rn)), rn)
        rois_num_per_level = [
            _wrap(jnp.asarray(np.bincount(
                img_of[lvl == min_level + li],
                minlength=len(rn)).astype(np.int32)))
            for li in range(n_levels)]
    restore = _wrap(jnp.asarray(restore_idx.reshape(-1, 1)))
    if rois_num_per_level is not None:
        return outs, restore, rois_num_per_level
    return outs, restore


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

class ConvNormActivation(object):
    """Conv2D + Norm + Activation block (reference vision/ops.py:1810).
    Returns an nn.Sequential."""

    def __new__(cls, in_channels, out_channels, kernel_size=3, stride=1,
                padding=None, groups=1, norm_layer=None,
                activation_layer=None, dilation=1, bias=None):
        from paddle_tpu import nn

        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        if activation_layer is None:
            activation_layer = nn.ReLU
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size,
                            stride, padding, dilation=dilation,
                            groups=groups,
                            bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        return nn.Sequential(*layers)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference vision/ops.py:1301)."""
    with open(filename, "rb") as f:
        raw = np.frombuffer(f.read(), np.uint8)
    return _wrap(jnp.asarray(raw))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode (reference vision/ops.py:1344, nvjpeg-backed).
    Host-side via Pillow when available."""
    try:
        from PIL import Image
    except ImportError as e:
        raise NotImplementedError(
            "decode_jpeg needs Pillow on the host (the reference uses "
            "nvjpeg, which has no TPU analog); install pillow or decode "
            "in the input pipeline") from e
    import io as _io

    raw = bytes(np.asarray(_data(x)).astype(np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return _wrap(jnp.asarray(arr))
