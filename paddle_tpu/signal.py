"""paddle.signal — short-time Fourier transform (reference:
python/paddle/signal.py stft:183 / istft:326 over frame+fft kernels).

TPU-native: framing is one strided gather (XLA WindowedGather fuses it),
the FFT rides XLA's native fft HLO via paddle_tpu.fft emitters, and the
istft overlap-add is a scatter-add — all static-shaped, jit-safe."""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["stft", "istft"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _frame(x, frame_length, hop_length):
    """(..., T) -> (..., n_frames, frame_length) via strided gather."""
    t = x.shape[-1]
    n = 1 + (t - frame_length) // hop_length
    idx = (jnp.arange(n)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None) -> Tensor:
    """(..., T) -> complex (..., n_fft//2+1 or n_fft, n_frames)
    (reference signal.py:183)."""
    xd = _data(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), xd.dtype)
    else:
        win = _data(window).astype(xd.dtype)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))
    if center:
        pad = n_fft // 2
        cfg = [(0, 0)] * (xd.ndim - 1) + [(pad, pad)]
        xd = jnp.pad(xd, cfg, mode=pad_mode)
    frames = _frame(xd, n_fft, hop_length) * win  # (..., n, n_fft)
    spec = jnp.fft.rfft(frames, axis=-1) if onesided \
        else jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    # paddle layout: (..., freq, n_frames)
    return Tensor._from_data(jnp.swapaxes(spec, -1, -2))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None) -> Tensor:
    """Inverse STFT with window-envelope-normalized overlap-add
    (reference signal.py:326)."""
    xd = _data(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _data(window).astype(jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))
    spec = jnp.swapaxes(xd, -1, -2)  # (..., n_frames, freq)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
        else jnp.fft.ifft(spec, axis=-1)
    if not return_complex and jnp.iscomplexobj(frames):
        frames = frames.real
    frames = frames * win
    n_frames = frames.shape[-2]
    t = n_fft + hop_length * (n_frames - 1)
    lead = frames.shape[:-2]
    out = jnp.zeros(lead + (t,), frames.dtype)
    env = jnp.zeros((t,), jnp.float32)
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])        # (n, n_fft)
    flat_idx = idx.reshape(-1)
    add = frames.reshape(lead + (-1,))
    out = out.at[..., flat_idx].add(add)
    env = env.at[flat_idx].add(
        jnp.broadcast_to(jnp.square(win), idx.shape).reshape(-1))
    out = out / jnp.maximum(env, 1e-11)
    if center:
        out = out[..., n_fft // 2: t - n_fft // 2]
    if length is not None:
        # reference istft: trim OR zero-pad to the requested length
        cur = out.shape[-1]
        if cur >= length:
            out = out[..., :length]
        else:
            cfg = [(0, 0)] * (out.ndim - 1) + [(0, length - cur)]
            out = jnp.pad(out, cfg)
    return Tensor._from_data(out)
