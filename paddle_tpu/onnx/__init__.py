"""paddle.onnx surface (reference: python/paddle/onnx/export.py, a hook
into the external paddle2onnx package).

Deliberately out of scope (see README "Scope"): the TPU deployment path
is ``paddle_tpu.jit.save`` — an AOT StableHLO module with swappable
(optionally int8-quantized) weights. This stub keeps the import surface
so reference code fails with an actionable message instead of an
AttributeError.
"""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is out of scope for the TPU build (it hooks the "
        "external paddle2onnx package). Use paddle_tpu.jit.save(layer, "
        "path, input_spec=...) to produce a serialized StableHLO module "
        "that paddle_tpu.jit.load runs on any XLA backend.")
