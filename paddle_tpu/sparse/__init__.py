"""paddle.sparse — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ (creation.py sparse_coo_tensor /
sparse_csr_tensor; unary/binary ops; matmul) over the phi sparse
kernels (paddle/phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse BCOO/BCSR — XLA-traceable
sparse formats whose matmuls lower to gather/scatter+MXU kernels. The
wrapper keeps paddle's API shape (indices [ndim, nnz], crows/cols), and
densifying ops interoperate with the regular Tensor/autograd world
through to_dense().
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_sparse", "is_sparse_coo", "is_sparse_csr",
           "add", "subtract", "multiply", "divide", "matmul", "relu",
           "tanh", "sqrt", "sin", "abs", "pow", "neg", "cast",
           "transpose", "softmax", "masked_matmul",
           # round-5 depth (reference unary/binary/multiary parity)
           "tan", "asin", "atan", "sinh", "asinh", "atanh", "square",
           "log1p", "expm1", "rad2deg", "deg2rad", "isnan", "coalesce",
           "sum", "reshape", "slice", "mv", "is_same_shape", "addmm",
           "pca_lowrank", "nn"]


class _SparseBase:
    def numel(self):
        return int(np.prod(self.shape))

    @property
    def ndim(self):
        return len(self.shape)

    def nnz(self):
        return int(self._mat.nse)

    @property
    def dtype(self):
        from paddle_tpu.core.dtype import convert_dtype

        return convert_dtype(self._mat.data.dtype)

    def to_dense(self) -> Tensor:
        return Tensor._from_data(self._mat.todense())

    def __repr__(self):
        return (f"{type(self).__name__}(shape={list(self.shape)}, "
                f"nnz={self.nnz()}, dtype={self.dtype.name})")


class SparseCooTensor(_SparseBase):
    """COO: indices [sparse_dim, nnz] + values [nnz, ...dense dims]."""

    def __init__(self, mat: "jsparse.BCOO"):
        self._mat = mat
        self.shape = tuple(mat.shape)

    def indices(self) -> Tensor:
        return Tensor._from_data(self._mat.indices.T.astype(jnp.int64))

    def values(self) -> Tensor:
        return Tensor._from_data(self._mat.data)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._mat.sum_duplicates())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._mat.sum_duplicates()))

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False


class SparseCsrTensor(_SparseBase):
    """CSR: crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, mat: "jsparse.BCSR"):
        self._mat = mat
        self.shape = tuple(mat.shape)

    def crows(self) -> Tensor:
        return Tensor._from_data(self._mat.indptr.astype(jnp.int64))

    def cols(self) -> Tensor:
        return Tensor._from_data(self._mat.indices.astype(jnp.int64))

    def values(self) -> Tensor:
        return Tensor._from_data(self._mat.data)

    def to_sparse_coo(self, sparse_dim=None) -> "SparseCooTensor":
        return SparseCooTensor(self._mat.to_bcoo())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def _data_of(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    """indices [sparse_dim, nnz] (paddle layout), values [nnz, ...]."""
    idx = jnp.asarray(_data_of(indices), jnp.int32).T  # -> [nnz, ndim]
    vals = _data_of(values)
    if dtype is not None:
        from paddle_tpu.core.dtype import to_jax

        vals = vals.astype(to_jax(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0)) + \
            tuple(vals.shape[1:])
    mat = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(mat)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    vals = _data_of(values)
    if dtype is not None:
        from paddle_tpu.core.dtype import to_jax

        vals = vals.astype(to_jax(dtype))
    mat = jsparse.BCSR(
        (vals, jnp.asarray(_data_of(cols), jnp.int32),
         jnp.asarray(_data_of(crows), jnp.int32)),
        shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(mat)


def is_sparse(x):
    return isinstance(x, _SparseBase)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


def _coo(x) -> "jsparse.BCOO":
    if isinstance(x, SparseCooTensor):
        return x._mat
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def _wrap_like(x, mat):
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(mat))
    return SparseCooTensor(mat)


# -- elementwise on values (zero-preserving unary ops) ----------------------
def _unary(fn):
    def op(x):
        m = _coo(x)
        return _wrap_like(x, jsparse.BCOO((fn(m.data), m.indices),
                                          shape=m.shape))

    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
sin = _unary(jnp.sin)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)


def pow(x, factor):
    m = _coo(x)
    return _wrap_like(x, jsparse.BCOO((m.data ** factor, m.indices),
                                      shape=m.shape))


def cast(x, index_dtype=None, value_dtype=None):
    from paddle_tpu.core.dtype import to_jax

    m = _coo(x)
    vals = m.data if value_dtype is None else m.data.astype(
        to_jax(value_dtype))
    idx = m.indices if index_dtype is None else m.indices.astype(
        to_jax(index_dtype))
    return _wrap_like(x, jsparse.BCOO((vals, idx), shape=m.shape))


# -- binary -----------------------------------------------------------------
def _binary(fn, densify_rhs=False):
    def op(x, y):
        if isinstance(y, _SparseBase) and not densify_rhs:
            out = fn(_coo(x).todense(), _coo(y).todense())
            return SparseCooTensor(jsparse.BCOO.fromdense(out))
        yv = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        out = fn(_coo(x).todense(), yv)
        return SparseCooTensor(jsparse.BCOO.fromdense(out))

    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)


def matmul(x, y) -> Tensor:
    """sparse @ dense -> dense (reference sparse.matmul); lowers to the
    XLA scatter/gather dot."""
    yv = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._from_data(_coo(x) @ yv)


def masked_matmul(x: Tensor, y: Tensor, mask) -> SparseCooTensor:
    """dense @ dense evaluated only at mask's nonzero positions
    (reference sparse.masked_matmul)."""
    m = _coo(mask)
    xv = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    rows = m.indices[:, 0]
    cols = m.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


def transpose(x, perm):
    m = _coo(x)
    return SparseCooTensor(m.transpose(tuple(perm)))


def softmax(x, axis=-1):
    """Softmax over the STORED values of each row, absent entries
    treated as -inf (reference phi/kernels/sparse/softmax_kernel.cc /
    sparse.nn.functional.softmax). 2-D sparse only; axis must be the
    last. This is the sparse-attention normalizer: rows with different
    sparsity patterns normalize over their own support."""
    if axis not in (-1, 1):
        raise ValueError("sparse softmax supports the last axis only")
    m = _coo(x).sum_duplicates(nse=_coo(x).nse)
    rows = m.indices[:, 0]
    nrows = m.shape[0]
    mx = jax.ops.segment_max(m.data, rows, num_segments=nrows)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    ex = jnp.exp(m.data - mx[rows])
    s = jax.ops.segment_sum(ex, rows, num_segments=nrows)
    out = ex / jnp.maximum(s[rows], 1e-38)
    return _wrap_like(x, jsparse.BCOO((out, m.indices), shape=m.shape))


# -- Tensor interop (reference: Tensor.to_sparse_coo / to_dense) ------------
def _tensor_to_sparse_coo(self, sparse_dim=None):
    nd = self._data.ndim
    n_dense = 0 if sparse_dim is None else nd - int(sparse_dim)
    return SparseCooTensor(jsparse.BCOO.fromdense(self._data,
                                                  n_dense=n_dense))


def _tensor_to_sparse_csr(self):
    return SparseCooTensor(
        jsparse.BCOO.fromdense(self._data)).to_sparse_csr()


Tensor.to_sparse_coo = _tensor_to_sparse_coo
Tensor.to_sparse_csr = _tensor_to_sparse_csr


# ---------------------------------------------------------------------------
# round-5 depth: the rest of the reference unary/binary/multiary surface
# (python/paddle/sparse/unary.py, binary.py, multiary.py). Zero-preserving
# unaries act on stored values only; structure-changing ops (reshape,
# slice, reductions) run DENSE on the MXU and re-sparsify — on TPU,
# sparsity is a memory format, not a compute strategy (the ASP 2:4 story),
# so format round-trips beat scalar scatter loops.
# ---------------------------------------------------------------------------

tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)


def isnan(x):
    m = _coo(x)
    return _wrap_like(x, jsparse.BCOO((jnp.isnan(m.data), m.indices),
                                      shape=m.shape))


def coalesce(x):
    """Merge duplicate indices (reference sparse.coalesce)."""
    m = _coo(x)
    return _wrap_like(x, m.sum_duplicates(nse=m.nse))


def sum(x, axis=None, dtype=None, keepdim=False):
    """Reference sparse.sum — result stays sparse (values computed via a
    dense reduction: reductions produce near-dense results anyway)."""
    dense = _coo(x).todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from paddle_tpu.core.dtype import to_jax

        out = out.astype(to_jax(dtype))
    if out.ndim == 0:
        return Tensor._from_data(out)
    return SparseCooTensor(jsparse.BCOO.fromdense(out))


def reshape(x, shape):
    dense = _coo(x).todense().reshape([int(s) for s in shape])
    return _wrap_like(x, jsparse.BCOO.fromdense(dense))


_py_slice = slice  # captured before ``def slice`` shadows the builtin


def slice(x, axes, starts, ends):
    """Reference sparse.slice: slice along ``axes``."""
    dense = _coo(x).todense()
    idx = [_py_slice(None)] * dense.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[int(a)] = _py_slice(int(s), int(e))
    return _wrap_like(x, jsparse.BCOO.fromdense(dense[tuple(idx)]))


def mv(x, vec) -> Tensor:
    """sparse matrix @ dense vector (reference sparse.mv)."""
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor._from_data(_coo(x) @ v)


def is_same_shape(x, y) -> bool:
    sx = x.shape if not isinstance(x, _SparseBase) else x._mat.shape
    sy = y.shape if not isinstance(y, _SparseBase) else y._mat.shape
    return tuple(sx) == tuple(sy)


def addmm(input, x, y, beta=1.0, alpha=1.0) -> Tensor:
    """beta*input + alpha*(x @ y) (reference sparse.addmm; x sparse)."""
    iv = input._data if isinstance(input, Tensor) else \
        _coo(input).todense()
    yv = y._data if isinstance(y, Tensor) else _coo(y).todense()
    return Tensor._from_data(beta * iv + alpha * (_coo(x) @ yv))


def pca_lowrank(x, q=None, center=True, niter=2):
    """Reference sparse.pca_lowrank — rank-q PCA of a sparse matrix.
    Computed via dense SVD (TPU MXU path; the randomized iteration of
    the reference is a CPU/GPU memory optimization)."""
    dense = _coo(x).todense()
    m, n = dense.shape
    k = int(q) if q is not None else min(6, m, n)
    if center:
        dense = dense - dense.mean(axis=0, keepdims=True)
    u, s, vt = jnp.linalg.svd(dense, full_matrices=False)
    return (Tensor._from_data(u[:, :k]), Tensor._from_data(s[:k]),
            Tensor._from_data(vt[:k].T))


# sparse.nn subpackage (imported last: it reuses this module's helpers)
from paddle_tpu.sparse import nn  # noqa: E402,F401
