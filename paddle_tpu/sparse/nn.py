"""paddle.sparse.nn — layers over sparse COO activations.

Reference: python/paddle/sparse/nn/ (functional/conv.py conv2d/conv3d +
submanifold variants over phi/kernels/sparse/gpu/conv_kernel.cu,
functional/pooling.py max_pool3d, layer/norm.py BatchNorm,
layer/activation.py) — CUDA gather-GEMM-scatter kernels over active
sites.

TPU-native design: on TPU the MXU wants dense tiles, so sparse conv
runs DENSE (densify -> lax.conv -> re-sparsify), and the SUBMANIFOLD
variants additionally mask the output to the input's active sites —
bit-identical semantics to the reference's site-gather kernels for the
point-cloud use case, with the sparse COO format preserved end to end.
This is the same design stance as ASP 2:4 (sparsity as a memory/
selection format; compute stays dense where the hardware wants it).
Layout follows the reference's sparse conv convention: channels-last
(NDHWC / NHWC), dense channel dim.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer
from paddle_tpu.sparse import (
    SparseCooTensor, _coo, _wrap_like,
)

__all__ = ["functional", "ReLU", "ReLU6", "LeakyReLU", "Softmax",
           "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "BatchNorm",
           "MaxPool3D"]


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(a) for a in v)
    return (int(v),) * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             subm):
    """Shared dense-compute sparse conv. x: SparseCoo [N, *spatial, C]
    (channels last, reference sparse conv layout); weight:
    [*k, C_in/groups, C_out] (reference sparse conv kernel layout)."""
    m = _coo(x)
    dense = m.todense()
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding, nd)
        pad = [(int(a), int(a)) for a in p]
    # NHWC/NDHWC x HWIO/DHWIO -> NHWC/NDHWC
    spec = ("NHWC", "HWIO", "NHWC") if nd == 2 else \
        ("NDHWC", "DHWIO", "NDHWC")
    dn = lax.conv_dimension_numbers(dense.shape, w.shape, spec)
    out = lax.conv_general_dilated(
        dense, w, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=int(groups))
    if bias is not None:
        b = bias._data if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    if subm:
        # submanifold: outputs exist ONLY at the input's active sites
        # (reference subm_conv kernels). Active = the COO INDEX SET, not
        # value!=0 — an explicitly-stored zero (e.g. a relu'd-to-zero
        # site) is still an active site and must keep its output.
        active = jnp.zeros(dense.shape[:-1], bool)
        active = active.at[tuple(m.indices[:, i]
                                 for i in range(m.indices.shape[1]))
                           ].set(True)
        out = jnp.where(active[..., None], out, 0.0)
    return SparseCooTensor(jsparse.BCOO.fromdense(out, n_dense=1))


class functional:
    """paddle.sparse.nn.functional."""

    @staticmethod
    def relu(x):
        from paddle_tpu import sparse as sp

        return sp.relu(x)

    @staticmethod
    def relu6(x):
        m = _coo(x)
        return _wrap_like(x, jsparse.BCOO(
            (jnp.clip(m.data, 0.0, 6.0), m.indices), shape=m.shape))

    @staticmethod
    def leaky_relu(x, negative_slope=0.01):
        m = _coo(x)
        return _wrap_like(x, jsparse.BCOO(
            (jnp.where(m.data > 0, m.data, negative_slope * m.data),
             m.indices), shape=m.shape))

    @staticmethod
    def softmax(x, axis=-1):
        from paddle_tpu import sparse as sp

        return sp.softmax(x, axis=axis)

    @staticmethod
    def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NHWC"):
        return _conv_nd(x, weight, bias, stride, padding, dilation,
                        groups, 2, subm=False)

    @staticmethod
    def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NDHWC"):
        return _conv_nd(x, weight, bias, stride, padding, dilation,
                        groups, 3, subm=False)

    @staticmethod
    def subm_conv2d(x, weight, bias=None, stride=1, padding=0,
                    dilation=1, groups=1, data_format="NHWC", key=None):
        return _conv_nd(x, weight, bias, stride, padding, dilation,
                        groups, 2, subm=True)

    @staticmethod
    def subm_conv3d(x, weight, bias=None, stride=1, padding=0,
                    dilation=1, groups=1, data_format="NDHWC", key=None):
        return _conv_nd(x, weight, bias, stride, padding, dilation,
                        groups, 3, subm=True)

    @staticmethod
    def max_pool3d(x, kernel_size, stride=None, padding=0,
                   data_format="NDHWC"):
        dense = _coo(x).todense()
        k = _pair(kernel_size, 3)
        s = _pair(stride if stride is not None else kernel_size, 3)
        p = _pair(padding, 3)
        out = lax.reduce_window(
            dense, -jnp.inf, lax.max,
            window_dimensions=(1,) + k + (1,),
            window_strides=(1,) + s + (1,),
            padding=((0, 0),) + tuple((a, a) for a in p) + ((0, 0),))
        out = jnp.where(jnp.isneginf(out), 0.0, out)
        return SparseCooTensor(jsparse.BCOO.fromdense(out, n_dense=1))


class _SparseConvBase(Layer):
    _nd = 2
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 key=None):
        super().__init__()
        import numpy as np

        from paddle_tpu.core import generator as gen

        nd = self._nd
        k = _pair(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        from paddle_tpu.nn.layer import Parameter

        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / max(fan_in, 1) ** 0.5
        w = jax.random.uniform(
            gen.active_key(), k + (in_channels // groups, out_channels),
            minval=-bound, maxval=bound)
        self.weight = Parameter(w)  # __setattr__ registers it
        if bias_attr is not False:
            b = jax.random.uniform(gen.active_key(), (out_channels,),
                                   minval=-bound, maxval=bound)
            self.bias = Parameter(b)
        else:
            self.bias = None

    def forward(self, x):
        return _conv_nd(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._nd, subm=self._subm)


class Conv2D(_SparseConvBase):
    """Reference: paddle.sparse.nn.Conv2D (functional/conv.py:693)."""
    _nd = 2


class Conv3D(_SparseConvBase):
    """Reference: paddle.sparse.nn.Conv3D (functional/conv.py:363)."""
    _nd = 3


class SubmConv2D(_SparseConvBase):
    """Reference: subm_conv2d (functional/conv.py:797) — output sparsity
    pinned to the input's active sites."""
    _nd = 2
    _subm = True


class SubmConv3D(_SparseConvBase):
    """Reference: subm_conv3d (functional/conv.py:469)."""
    _nd = 3
    _subm = True


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, self._axis)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding

    def forward(self, x):
        return functional.max_pool3d(x, self._k, self._s, self._p)


class BatchNorm(Layer):
    """Reference: paddle.sparse.nn.BatchNorm (layer/norm.py) — batch
    norm over the dense channel dim of the STORED values (statistics
    over active sites only, matching the reference's semantics)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from paddle_tpu.nn.layer import Parameter

        self._momentum = momentum
        self._eps = epsilon
        self.weight = Parameter(jnp.ones((num_features,)))
        self.bias = Parameter(jnp.zeros((num_features,)))
        self.register_buffer(
            "_mean", Tensor._from_data(jnp.zeros((num_features,))))
        self.register_buffer(
            "_variance", Tensor._from_data(jnp.ones((num_features,))))

    def forward(self, x):
        m = _coo(x)
        vals = m.data  # [nnz, C]
        if self.training:
            mu = vals.mean(axis=0)
            var = vals.var(axis=0)
            mom = self._momentum
            self._mean._data = mom * self._mean._data + (1 - mom) * mu
            self._variance._data = (mom * self._variance._data
                                    + (1 - mom) * var)
        else:
            mu, var = self._mean._data, self._variance._data
        wd = self.weight._data
        bd = self.bias._data
        out = (vals - mu) / jnp.sqrt(var + self._eps) * wd + bd
        return _wrap_like(x, jsparse.BCOO((out, m.indices),
                                          shape=m.shape))
