"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast at amp/auto_cast.py:860, O1/O2
lists amp_lists.py, GradScaler grad_scaler.py). TPU-native: the compute
dtype is bfloat16, which needs NO loss scaling (same exponent range as
f32) — GradScaler is provided for API parity and for float16 paths, but
with bf16 it is an identity. auto_cast works by intercepting op dispatch:
inputs of white-listed ops are cast to the compute dtype at the registry
boundary (the same point where the reference's generated AMP branch sits,
eager_gen.py:1885).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from paddle_tpu.core.dtype import convert_dtype, to_jax
from paddle_tpu.core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "is_bfloat16_supported",
           "is_float16_supported", "white_list", "black_list"]

_state = threading.local()

# O1 lists (reference: python/paddle/amp/amp_lists.py)
WHITE_LIST = {
    "matmul", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "scaled_dot_product_attention",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax",
    "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "binary_cross_entropy", "binary_cross_entropy_with_logits", "nll_loss",
    "kl_div", "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "rms_norm", "mean", "sum", "cumsum", "var", "std", "norm",
}

white_list = WHITE_LIST
black_list = BLACK_LIST


def amp_state():
    return getattr(_state, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity; level O1 = per-op lists, O2 = cast
    everything floating to the compute dtype (except black list)."""
    prev = amp_state()
    if enable:
        wl = set(WHITE_LIST)
        bl = set(BLACK_LIST)
        if custom_white_list:
            wl |= set(custom_white_list)
        if custom_black_list:
            bl |= set(custom_black_list)
        _state.amp = {
            "dtype": convert_dtype(dtype),
            "level": level,
            "white": wl,
            "black": bl,
        }
    else:
        _state.amp = None
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def cast_for_op(op_name, datas):
    """Called by the op registry before emission: returns datas cast per the
    active AMP policy."""
    st = amp_state()
    if st is None:
        return datas
    dt = to_jax(st["dtype"])
    level = st["level"]
    if op_name in st["black"]:
        # compute in f32
        return [d.astype(jnp.float32)
                if hasattr(d, "dtype") and jnp.issubdtype(d.dtype,
                                                          jnp.floating)
                else d for d in datas]
    if level == "O2" or op_name in st["white"]:
        return [d.astype(dt)
                if hasattr(d, "dtype") and d.dtype == jnp.float32
                else d for d in datas]
    return datas


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, **kw):
    """O2 decoration: cast model params to the compute dtype (reference:
    paddle.amp.decorate). Master weights: for bf16 on TPU we keep f32 master
    copies inside optimizer slots when master_weight=True."""
    from paddle_tpu.nn.layer import Layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Loss scaler (reference: python/paddle/amp/grad_scaler.py). With
    bfloat16 this is an identity pass-through (bf16 needs no scaling);
    dynamic scaling logic is kept for fp16 parity."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True,
                 max_consecutive_skips=50):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._already_unscaled = False
        # divergence guard: N consecutive found_inf skips means the run
        # is NaN for real, not a transient overflow — halving the scale
        # forever would just hide it (0 disables the guard)
        self._max_consecutive_skips = int(max_consecutive_skips or 0)
        self._skipped_steps = 0
        self._consecutive_skips = 0

    @property
    def skipped_steps(self) -> int:
        """Total optimizer steps skipped because of non-finite grads."""
        return self._skipped_steps

    def _check_diverged(self):
        if self._max_consecutive_skips and \
                self._consecutive_skips >= self._max_consecutive_skips:
            raise RuntimeError(
                f"training diverged: {self._consecutive_skips} "
                f"consecutive steps produced non-finite gradients "
                f"(loss scale is down to {self._scale}); restore from a "
                f"checkpoint with a lower learning rate instead of "
                f"letting the scaler halve the scale forever. Raise "
                f"GradScaler(max_consecutive_skips=...) to tolerate "
                f"longer bursts.")

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._already_unscaled:
            return
        import jax.numpy as jnp_

        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = p.grad._data * inv
                if bool(jnp_.any(~jnp_.isfinite(g))):
                    found = True
                p.grad._data = g
        self._found_inf = found
        self._already_unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        self._already_unscaled = False
        if self._found_inf:
            self._skipped_steps += 1
            self._consecutive_skips += 1
        else:
            self._consecutive_skips = 0
        if self._dynamic:
            if self._found_inf:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every:
                    self._scale = max(self._scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            else:
                self._good_steps += 1
                self._bad_steps = 0
                if self._good_steps >= self._incr_every:
                    self._scale *= self._incr_ratio
                    self._good_steps = 0
        self._check_diverged()

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        from paddle_tpu.core.tensor import Tensor as T
        return T(self._scale)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps,
                "skipped_steps": self._skipped_steps,
                "consecutive_skips": self._consecutive_skips}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._skipped_steps = int(state.get("skipped_steps", 0))
        self._consecutive_skips = int(state.get("consecutive_skips", 0))


# -- compiled-step loss scaling (shared by TrainStep/ParallelTrainStep) ----
def scaler_init_state(scaler):
    """[scale, good_steps, bad_steps, skipped_total, consecutive_skips]
    as a traced f32 vector, or None when scaling is off (reference
    HybridParallelGradScaler state; the two skip counters back the
    divergence guard and the observability surface)."""
    import jax.numpy as jnp

    if scaler is None or not scaler.is_enable():
        return None
    return jnp.asarray([scaler._scale, float(scaler._good_steps),
                        float(scaler._bad_steps),
                        float(scaler._skipped_steps),
                        float(scaler._consecutive_skips)],
                       dtype=jnp.float32)


def scaler_unscale_and_check(grads, state):
    """Unscale grads by state's scale; found_inf = any nonfinite grad."""
    import jax.numpy as jnp

    inv = 1.0 / state[0]
    gs = [g * inv for g in grads]
    found = jnp.zeros((), jnp.bool_)
    for g in gs:
        found = found | jnp.any(~jnp.isfinite(g))
    return gs, found


def scaler_update_state(scaler, state, found):
    """Dynamic loss-scale schedule as pure jnp (mirrors GradScaler.update)."""
    import jax.numpy as jnp

    scale, good, bad = state[0], state[1], state[2]
    skipped2 = state[3] + jnp.where(found, 1.0, 0.0)
    consec2 = jnp.where(found, state[4] + 1.0, 0.0)
    if not scaler._dynamic:
        return jnp.stack([scale, good, bad, skipped2, consec2])
    bad2 = jnp.where(found, bad + 1.0, 0.0)
    good2 = jnp.where(found, 0.0, good + 1.0)
    dec = bad2 >= scaler._decr_every
    inc = good2 >= scaler._incr_every
    scale2 = jnp.where(dec, jnp.maximum(scale * scaler._decr_ratio, 1.0),
                       jnp.where(inc & ~found, scale * scaler._incr_ratio,
                                 scale))
    return jnp.stack([scale2, jnp.where(inc, 0.0, good2),
                      jnp.where(dec, 0.0, bad2), skipped2, consec2])


def scaler_sync_from_state(scaler, state):
    """Write the traced state back onto the python GradScaler, and apply
    the divergence guard: a long run of consecutive non-finite steps in
    the COMPILED path must fail as loudly as the eager one."""
    import numpy as np

    s = np.asarray(state)
    scaler._scale = float(s[0])
    scaler._good_steps = int(s[1])
    scaler._bad_steps = int(s[2])
    if len(s) > 4:  # state from an older checkpoint may be 3 wide
        scaler._skipped_steps = int(s[3])
        scaler._consecutive_skips = int(s[4])
        scaler._check_diverged()


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return True


# register the dispatch-boundary hook
from paddle_tpu.ops import registry as _registry  # noqa: E402

_registry.set_amp_hook(cast_for_op)
