"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on XLA (via JAX) + Pallas instead of CUDA/cuDNN/NCCL.

Layer map vs the reference (see SURVEY.md):
  L0/L1  core/{dtype,place,flags,generator}  <- phi/common + backends
  L2     core/tensor + ops/ (yaml registry)  <- phi kernels + api yaml codegen
  L4a    autograd/                           <- fluid/eager
  L4b    jit/                                <- PIR + new_executor + CINN (XLA)
  L6     nn/, optimizer/, io/, amp/          <- python/paddle/*
  L3/L7  distributed/                        <- phi/core/distributed + fleet
  L8     vision/, hapi/                      <- python/paddle/vision, hapi
"""
from __future__ import annotations

__version__ = "0.1.0"

from paddle_tpu.core.tensor import Tensor, is_tensor, to_tensor  # noqa: F401
from paddle_tpu.core.dtype import (  # noqa: F401
    DType, dtype, bool_ as bool8, uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64, complex64, complex128,
    get_default_dtype, set_default_dtype,
)
from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CustomPlace, Place, TPUPlace, get_device, set_device,
    is_compiled_with_tpu,
)
from paddle_tpu.core.flags import get_flags, set_flags  # noqa: F401
from paddle_tpu.core.generator import (  # noqa: F401
    Generator, get_rng_state, seed, set_rng_state,
)

# op surface: every registry op becomes a paddle_tpu.<op> function
from paddle_tpu import ops  # noqa: F401
from paddle_tpu.ops.registry import API as _OPS_API

globals().update(_OPS_API)

from paddle_tpu.autograd import grad, no_grad, enable_grad, set_grad_enabled  # noqa: F401,E402
from paddle_tpu import autograd  # noqa: F401,E402
from paddle_tpu import nn  # noqa: F401,E402
from paddle_tpu import optimizer  # noqa: F401,E402
from paddle_tpu import io  # noqa: F401,E402
from paddle_tpu import amp  # noqa: F401,E402
from paddle_tpu import jit  # noqa: F401,E402
from paddle_tpu import framework  # noqa: F401,E402
from paddle_tpu.framework.io_utils import save, load  # noqa: F401,E402
from paddle_tpu.framework.param_attr import ParamAttr  # noqa: F401,E402
from paddle_tpu import vision  # noqa: F401,E402
from paddle_tpu import metric  # noqa: F401
from paddle_tpu import hapi  # noqa: F401,E402
from paddle_tpu.hapi.model import Model  # noqa: F401,E402
from paddle_tpu import profiler  # noqa: F401,E402
from paddle_tpu import incubate  # noqa: F401,E402,E402

# the fft MODULE shadows the raw 1-D fft op exported by the registry
# (paddle.fft is a namespace in the reference; paddle.fft.fft the op)
import paddle_tpu.fft  # noqa: F401,E402
import sys as _sys  # noqa: E402

fft = _sys.modules["paddle_tpu.fft"]
from paddle_tpu import distribution  # noqa: F401,E402
from paddle_tpu import device  # noqa: F401,E402
from paddle_tpu import audio  # noqa: F401,E402
from paddle_tpu import text  # noqa: F401,E402

# numpy-style casting helper used across paddle code
from paddle_tpu.ops.registry import API as _api


def einsum(equation, *operands):
    """paddle.einsum(equation, *operands) — the registry op takes the
    operand list first, the public API leads with the equation
    (reference python/paddle/tensor/einsum.py)."""
    return _api["einsum"](list(operands), equation)


def randn_like(x, dtype=None):
    return _api["randn"](x.shape, dtype=dtype or x.dtype)


def add_n(inputs):
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


def numel(x):
    return x.size


def tolist(x):
    return x.tolist()


def flops(net, input_size=None, inputs=None, **kw):
    from paddle_tpu.hapi.model_summary import flops as _flops

    return _flops(net, input_size=input_size, inputs=inputs, **kw)


def summary(net, input_size=None, dtypes=None, input=None):
    from paddle_tpu.hapi.model_summary import summary as _summary

    return _summary(net, input_size=input_size, dtypes=dtypes, input=input)




def in_dynamic_mode() -> bool:
    from paddle_tpu import static as _static
    from paddle_tpu.jit.trace import in_tracing
    return not in_tracing() and not _static.in_static_mode()


def disable_static():
    from paddle_tpu import static as _static
    _static._disable()


def enable_static():
    """Enter Program mode (reference: paddle.enable_static). Registry
    ops on static Variables are recorded into the default Program and
    executed by paddle_tpu.static.Executor — see paddle_tpu/static/."""
    from paddle_tpu import static as _static
    _static._enable()


def is_grad_enabled():
    from paddle_tpu.autograd import engine
    return engine.is_grad_enabled()


def device_count():
    from paddle_tpu.core.place import device_count as _dc
    return _dc()
from paddle_tpu import sparse  # noqa: F401,E402
from paddle_tpu import geometric  # noqa: F401,E402
from paddle_tpu import onnx  # noqa: F401,E402
from paddle_tpu import quantization  # noqa: F401,E402
from paddle_tpu import static  # noqa: F401,E402
import paddle_tpu.signal  # noqa: F401,E402
from paddle_tpu import version  # noqa: E402,F401
from paddle_tpu import utils  # noqa: E402,F401
from paddle_tpu import linalg  # noqa: E402,F401

__version__ = version.full_version


class iinfo:
    """paddle.iinfo (reference: pybind iinfo over phi dtypes)."""

    def __init__(self, dtype):
        import jax.numpy as jnp

        from paddle_tpu.core.dtype import to_jax

        info = jnp.iinfo(to_jax(dtype))
        self.max = int(info.max)
        self.min = int(info.min)
        self.bits = int(info.bits)
        self.dtype = str(dtype)


class finfo:
    """paddle.finfo (reference: pybind finfo over phi dtypes)."""

    def __init__(self, dtype):
        import jax.numpy as jnp

        from paddle_tpu.core.dtype import to_jax

        # jnp.finfo handles ml_dtypes (bfloat16) where np.finfo cannot
        info = jnp.finfo(to_jax(dtype))
        self.max = float(info.max)
        self.min = float(info.min)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)
        self.bits = int(info.bits)
        self.dtype = str(dtype)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """paddle.bucketize (reference tensor/search.py:1065): searchsorted
    with 1-D boundaries."""
    from paddle_tpu.ops.registry import API as _api

    return _api["searchsorted"](sorted_sequence, x, out_int32=out_int32,
                                right=right)


def get_cuda_rng_state():
    """Device RNG state list (reference get_cuda_rng_state returns one
    state per GPU; here the threefry generator state — one device RNG
    stream per process)."""
    from paddle_tpu.core.generator import default_generator

    return [default_generator.get_state()]


def set_cuda_rng_state(state_list):
    from paddle_tpu.core.generator import default_generator

    default_generator.set_state(state_list[0])

# top-level namespace completion: in-place variants, aliases, dtype
# predicates, utilities (reference python/paddle/__init__.py __all__)
from paddle_tpu import compat_extra as _compat_extra  # noqa: E402

globals().update(_compat_extra.EXPORTS)

# accelerator-place compat aliases: code written against the reference's
# GPU surface keeps working — CUDAPlace maps to this build's accelerator
from paddle_tpu.core.place import TPUPlace as CUDAPlace  # noqa: E402,F401
from paddle_tpu.core.place import CPUPlace as CUDAPinnedPlace  # noqa: E402,F401
from paddle_tpu.distributed.parallel_wrapper import DataParallel  # noqa: E402,F401

# dtype name parity: paddle.bool is the boolean dtype (shadows the
# builtin only as a module attribute, same as the reference)
bool = bool8  # noqa: A001
