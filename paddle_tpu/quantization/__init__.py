"""paddle.quantization — PTQ / QAT.

Reference: python/paddle/quantization/ — config.py QuantConfig
(add_layer_config/add_type_config), ptq.py PTQ (observer insertion →
calibrate → convert), qat.py QAT (fake-quant insertion), observers
(AbsmaxObserver ...) and fake quanters (FakeQuanterWithAbsMaxObserver).

TPU-native: fake-quant is a traced elementwise op with a
straight-through-estimator custom VJP, so QAT trains inside the same
compiled step; observers are host-side running statistics updated at
eager/calibration time. int8 execution itself is simulated
(quantize→dequantize), matching the reference's simulated-quant
training path; true int8 serving is an inference-engine concern.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Type

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer

__all__ = ["QuantConfig", "PTQ", "QAT", "AbsmaxObserver", "EMAObserver",
           "FakeQuanterWithAbsMaxObserver", "quant_dequant"]


# ---------------------------------------------------------------------------
# fake quant with STE
# ---------------------------------------------------------------------------
@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # straight-through inside the clip range, zero outside
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-8)).astype(g.dtype)
    return g * mask, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


from paddle_tpu.ops import registry as _registry
from paddle_tpu.ops.registry import register_emitter as _register


@_register(name="fake_quant_dequant")
def _fake_quant_emitter(x, scale=1.0, qmax=127.0):
    """Registry op so the eager autograd tape records the STE vjp —
    calling the raw jax function would silently detach quantized
    weights from their gradients."""
    return _fake_quant(x, jnp.asarray(scale, x.dtype),
                       jnp.asarray(qmax, x.dtype))


if "fake_quant_dequant" not in _registry.OPS:
    _registry.build_registry([
        {"op": "fake_quant_dequant", "tensor_args": ["x"],
         "methods": []}])


def quant_dequant(x, scale, bit_length=8):
    """Simulated quantization (quantize->dequantize) of a Tensor."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = float(scale._data) if isinstance(scale, Tensor) else float(scale)
    return _registry.API["fake_quant_dequant"](x, scale=s, qmax=qmax)


# ---------------------------------------------------------------------------
# observers
# ---------------------------------------------------------------------------
class _ObserverBase:
    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._scale = 0.0

    def observe(self, x: Tensor):
        raise NotImplementedError

    def scale(self) -> float:
        return max(self._scale, 1e-8)

    def quant_axis(self):
        return -1


class AbsmaxObserver(_ObserverBase):
    """Running max(|x|) (reference observers/abs_max.py)."""

    def observe(self, x):
        v = float(np.max(np.abs(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x))))
        self._scale = max(self._scale, v)


class EMAObserver(_ObserverBase):
    """Exponential moving average of max(|x|) (reference
    observers/ema.py semantics)."""

    def __init__(self, quant_bits=8, decay=0.9):
        super().__init__(quant_bits)
        self.decay = decay
        self._init = False

    def observe(self, x):
        v = float(np.max(np.abs(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x))))
        if not self._init:
            self._scale, self._init = v, True
        else:
            self._scale = self.decay * self._scale + (1 - self.decay) * v


class FakeQuanterWithAbsMaxObserver(_ObserverBase):
    """QAT quanter: observes while training and fake-quants in the same
    pass (reference quanters/abs_max.py)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._init = False

    def observe(self, x):
        # under tracing we cannot host-read; keep last calibrated scale
        xd = x._data if isinstance(x, Tensor) else x
        if isinstance(xd, jax.core.Tracer):
            return
        v = float(np.max(np.abs(np.asarray(xd))))
        if not self._init:
            self._scale, self._init = v, True
        else:
            self._scale = self.moving_rate * self._scale + \
                (1 - self.moving_rate) * v


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_act = activation
        self._global_w = weight
        self._layer_cfg = {}
        self._type_cfg: Dict[Type, tuple] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        # NOTE: layer identity is matched by id(); pair with
        # quantize(..., inplace=True) — a deepcopy changes identities
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def _for_layer(self, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._global_act or self._global_w:
            return (self._global_act, self._global_w)
        return None


def _make(factory, default_cls):
    if factory is None:
        return default_cls()
    if isinstance(factory, type):
        return factory()
    if callable(factory):
        try:
            return factory()
        except TypeError:
            return factory
    return factory


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------
class QuantedLayer(Layer):
    """Wraps Linear/Conv2D: observe activations (+fake-quant in QAT)."""

    def __init__(self, inner, act_observer, w_observer, train_quant):
        super().__init__()
        self.inner = inner
        self.act_observer = act_observer
        self.w_observer = w_observer
        self.train_quant = train_quant  # QAT: fake-quant during forward
        self.w_observer.observe(inner.weight)

    def forward(self, x):
        self.act_observer.observe(x)
        if self.train_quant:
            # re-observe the (training) weight every pass: a frozen init
            # scale would clip growing weights and the STE would zero
            # their gradients, stalling QAT (reference quanters observe
            # per forward)
            self.w_observer.observe(self.inner.weight)
            x = quant_dequant(x, self.act_observer.scale(),
                              self.act_observer.quant_bits)
            w = quant_dequant(self.inner.weight, self.w_observer.scale(),
                              self.w_observer.quant_bits)
            return self._apply_inner(x, w)
        return self.inner(x)

    def _apply_inner(self, x, w):
        from paddle_tpu import ops

        if isinstance(self.inner, nn.Linear):
            return ops.linear(x, w, self.inner.bias)
        if isinstance(self.inner, nn.Conv2D):
            c = self.inner
            return ops.conv2d(x, w, c.bias, stride=c.stride,
                              padding=c.padding, dilation=c.dilation,
                              groups=c.groups)
        raise NotImplementedError(type(self.inner))


class ConvertedQuantLayer(Layer):
    """Post-convert form: the weight is stored as an INT8 buffer + scale
    and dequantized inside the compiled graph (activations
    quant-dequant'ed) — the reference's convert() output feeding int8
    export (static/quantization/post_training_quantization.py role).

    The original f32 weight is NOT kept: state_dict/jit.save carry the
    int8 buffer (~4x smaller), and the exported StableHLO takes the int8
    array as an input with the dequant multiply compiled in."""

    def __init__(self, q: QuantedLayer):
        super().__init__()
        inner = q.inner
        self._is_linear = isinstance(inner, nn.Linear)
        if not self._is_linear:
            self._stride = inner.stride
            self._padding = inner.padding
            self._dilation = inner.dilation
            self._groups = inner.groups
        bits = q.w_observer.quant_bits
        qmax = float(2 ** (bits - 1) - 1)
        w = inner.weight.numpy()
        self.w_scale = float(q.w_observer.scale())
        qw = np.clip(np.round(w / self.w_scale * qmax), -qmax, qmax
                     ).astype(np.int8)
        self.register_buffer("qweight", Tensor(qw))
        self.bias = inner.bias  # reused Parameter (may be None)
        self.act_scale = float(q.act_observer.scale())
        self.act_bits = q.act_observer.quant_bits
        self._qmax = qmax

    def forward(self, x):
        from paddle_tpu import ops

        x = quant_dequant(x, self.act_scale, self.act_bits)
        w = ops.cast(self.qweight, "float32") * (self.w_scale / self._qmax)
        if self._is_linear:
            return ops.linear(x, w, self.bias)
        return ops.conv2d(x, w, self.bias, stride=self._stride,
                          padding=self._padding, dilation=self._dilation,
                          groups=self._groups)


_DEFAULT_TYPES = (nn.Linear, nn.Conv2D)


def _replace_child(parent, key, new_layer):
    """Replace a sublayer IN PLACE in the parent's registry: setattr
    would delete+reinsert the key, moving it to the end of the ordered
    _sub_layers dict and scrambling Sequential execution order."""
    if key in getattr(parent, "_sub_layers", {}):
        parent._sub_layers[key] = new_layer
    else:
        setattr(parent, key, new_layer)


def _swap_layers(model, config, train_quant, default_act, default_w):
    for name, sub in list(model.named_sublayers(include_self=False)):
        parent = model
        parts = name.split(".")
        for p in parts[:-1]:
            parent = getattr(parent, p)
        child = getattr(parent, parts[-1])
        if isinstance(child, _DEFAULT_TYPES):
            cfg = config._for_layer(child) if config else None
            act = _make(cfg[0] if cfg else None, default_act)
            wob = _make(cfg[1] if cfg else None, default_w)
            _replace_child(parent, parts[-1],
                           QuantedLayer(child, act, wob, train_quant))
    return model


class PTQ:
    """Post-training quantization (reference ptq.py): insert observers,
    run calibration batches, convert()."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self.config, train_quant=False,
                            default_act=AbsmaxObserver,
                            default_w=AbsmaxObserver)

    def convert(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        for name, sub in list(model.named_sublayers(include_self=False)):
            if isinstance(sub, QuantedLayer):
                parent = model
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                _replace_child(parent, parts[-1],
                               ConvertedQuantLayer(sub))
        return model


class QAT:
    """Quantization-aware training (reference qat.py): fake-quant with
    STE inside the training graph."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self.config, train_quant=True,
                            default_act=FakeQuanterWithAbsMaxObserver,
                            default_w=FakeQuanterWithAbsMaxObserver)

    convert = PTQ.convert
